"""Solvers: line search, conjugate gradient, LBFGS.

Reference: optimize/Solver.java:48 (optimize()) + :55 (factory dispatching on
OptimizationAlgorithm), optimize/solvers/{StochasticGradientDescent.java:51-72,
BaseOptimizer.java, BackTrackLineSearch.java, ConjugateGradient.java, LBFGS.java,
LineGradientDescent.java}.

TPU-first design: instead of the reference's per-op Java loops, each solver
works on ONE flattened parameter vector; loss+gradient for a minibatch is a
single jitted XLA computation reused across line-search probes (probes only
re-run the compiled executable with a new vector — no retrace).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _flatten_spec(params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    return treedef, shapes, sizes


def _ravel(params):
    leaves = jax.tree_util.tree_leaves(params)
    return jnp.concatenate([jnp.ravel(l) for l in leaves]) if leaves else jnp.zeros((0,))


def _unravel(vec, treedef, shapes, sizes):
    out, off = [], 0
    for shape, size in zip(shapes, sizes):
        out.append(jnp.reshape(vec[off:off + size], shape))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


class BackTrackLineSearch:
    """Backtracking line search with Armijo sufficient-decrease
    (reference: optimize/solvers/BackTrackLineSearch.java)."""

    def __init__(self, score_fn, max_iterations=5, c1=1e-4, rho=0.5):
        self.score_fn = score_fn          # vec -> score (compiled)
        self.max_iterations = int(max_iterations)
        self.c1 = c1
        self.rho = rho

    def optimize(self, w, f0, g, direction, initial_step=1.0):
        """Returns step size along `direction` satisfying sufficient decrease
        (0.0 if none found)."""
        slope = float(jnp.vdot(g, direction))
        if slope >= 0:   # not a descent direction — reject
            return 0.0
        step = initial_step
        for _ in range(self.max_iterations):
            f_new = float(self.score_fn(w + step * direction))
            if np.isfinite(f_new) and f_new <= f0 + self.c1 * step * slope:
                return step
            step *= self.rho
        return 0.0


def _shapes_key(x, y):
    def one(v):
        if isinstance(v, (list, tuple)):
            return tuple(tuple(vv.shape) for vv in v)
        return tuple(v.shape)
    return (one(x), one(y))


class BaseFlatSolver:
    """Shared machinery: compiled (score, grad) on flattened params.

    Line-search probes run with train=False so the objective is deterministic
    (no dropout); after the optimization loop one train=True pass refreshes
    layer states (BatchNorm running statistics) — the reference's CG/LBFGS
    equally runs its line searches on a fixed objective per iteration.
    Compiled fns are cached per input shape, so repeated fit_batch calls
    reuse the same XLA executables.
    """

    def __init__(self, model, max_iterations=1, line_search_iterations=5):
        self.model = model
        self.max_iterations = int(max_iterations)
        self.line_search_iterations = int(line_search_iterations)
        self._fns_cache = {}

    def _call_loss(self, p, states, x, y, mask, label_mask, train):
        is_graph = isinstance(x, (list, tuple))
        if is_graph:
            return self.model._loss(p, states, x, y, train=train, rng=None,
                                    masks=mask, label_masks=label_mask)
        return self.model._loss(p, states, x, y, train=train, rng=None,
                                mask=mask, label_mask=label_mask)

    def _fns(self, x, y, mask, label_mask):
        treedef, shapes, sizes = _flatten_spec(self.model.params)
        key = (_shapes_key(x, y), tuple(shapes))
        if key not in self._fns_cache:
            def loss_vec(vec, x, y, mask, label_mask, states):
                p = _unravel(vec, treedef, shapes, sizes)
                s, _ = self._call_loss(p, states, x, y, mask, label_mask, False)
                return s

            self._fns_cache[key] = (jax.jit(jax.value_and_grad(loss_vec)),
                                    jax.jit(loss_vec))
        # only the compiled fns are cached; the batch and layer states are
        # bound per call, so every fit_batch optimizes the CURRENT minibatch
        vg, score = self._fns_cache[key]
        states = self.model.states
        vg_b = lambda w: vg(w, x, y, mask, label_mask, states)
        score_b = lambda w: score(w, x, y, mask, label_mask, states)
        return (treedef, shapes, sizes), vg_b, score_b

    def optimize(self, x, y, mask=None, label_mask=None):
        raise NotImplementedError

    def _finish(self, w, spec, score, x=None, y=None, mask=None, label_mask=None):
        treedef, shapes, sizes = spec
        params = jax.tree_util.tree_map(
            jnp.asarray, _unravel(w, treedef, shapes, sizes))
        self.model.params = params
        if x is not None:
            # one train-mode pass to refresh BN running stats etc.
            _, aux = self._call_loss(params, self.model.states, x, y, mask,
                                     label_mask, True)
            self.model.states = aux[0]
        self.model.score_value = float(score)


class LineGradientDescent(BaseFlatSolver):
    """Steepest descent with line search (reference: LineGradientDescent.java)."""

    def optimize(self, x, y, mask=None, label_mask=None):
        spec, vg, score_fn = self._fns(x, y, mask, label_mask)
        w = _ravel(self.model.params)
        ls = BackTrackLineSearch(score_fn, self.line_search_iterations)
        for _ in range(self.max_iterations):
            f, g = vg(w)
            step = ls.optimize(w, float(f), g, -g)
            if step == 0.0:
                break
            w = w - step * g
        self._finish(w, spec, score_fn(w), x, y, mask, label_mask)
        return self.model


class ConjugateGradient(BaseFlatSolver):
    """Nonlinear CG (Polak-Ribiere+) with restart on non-descent
    (reference: optimize/solvers/ConjugateGradient.java)."""

    def optimize(self, x, y, mask=None, label_mask=None):
        spec, vg, score_fn = self._fns(x, y, mask, label_mask)
        w = _ravel(self.model.params)
        ls = BackTrackLineSearch(score_fn, self.line_search_iterations)
        g_prev = None
        d = None
        for _ in range(self.max_iterations):
            f, g = vg(w)
            if g_prev is None:
                d = -g
            else:
                beta = float(jnp.vdot(g, g - g_prev) / jnp.vdot(g_prev, g_prev))
                beta = max(0.0, beta)  # PR+ restart
                d = -g + beta * d
            step = ls.optimize(w, float(f), g, d)
            if step == 0.0:
                # restart with steepest descent once before giving up
                d = -g
                step = ls.optimize(w, float(f), g, d)
                if step == 0.0:
                    break
            w = w + step * d
            g_prev = g
        self._finish(w, spec, score_fn(w), x, y, mask, label_mask)
        return self.model


class LBFGS(BaseFlatSolver):
    """Limited-memory BFGS, two-loop recursion (reference:
    optimize/solvers/LBFGS.java; memory m=4 like the reference default)."""

    def __init__(self, model, max_iterations=1, line_search_iterations=5, m=4):
        super().__init__(model, max_iterations, line_search_iterations)
        self.m = int(m)

    def optimize(self, x, y, mask=None, label_mask=None):
        spec, vg, score_fn = self._fns(x, y, mask, label_mask)
        w = _ravel(self.model.params)
        ls = BackTrackLineSearch(score_fn, self.line_search_iterations)
        s_hist, y_hist = [], []
        f, g = vg(w)
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = g
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / float(jnp.vdot(yv, s))
                a = rho * float(jnp.vdot(s, q))
                alphas.append((a, rho, s, yv))
                q = q - a * yv
            if y_hist:
                gamma = float(jnp.vdot(s_hist[-1], y_hist[-1]) /
                              jnp.vdot(y_hist[-1], y_hist[-1]))
                q = gamma * q
            for a, rho, s, yv in reversed(alphas):
                b = rho * float(jnp.vdot(yv, q))
                q = q + (a - b) * s
            d = -q
            step = ls.optimize(w, float(f), g, d)
            if step == 0.0:
                d = -g
                step = ls.optimize(w, float(f), g, d)
                if step == 0.0:
                    break
            w_new = w + step * d
            f_new, g_new = vg(w_new)
            s_hist.append(w_new - w)
            y_hist.append(g_new - g)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            w, f, g = w_new, f_new, g_new
        self._finish(w, spec, f, x, y, mask, label_mask)
        return self.model


def make_solver(algo, model, max_iterations=1, line_search_iterations=5):
    """Factory (reference: optimize/Solver.java:55)."""
    from ...nn.conf.configuration import OptimizationAlgorithm as OA
    table = {
        OA.LINE_GRADIENT_DESCENT: LineGradientDescent,
        OA.CONJUGATE_GRADIENT: ConjugateGradient,
        OA.LBFGS: LBFGS,
    }
    if algo not in table:
        raise ValueError(f"no flat solver for {algo}")
    return table[algo](model, max_iterations=max_iterations,
                       line_search_iterations=line_search_iterations)
