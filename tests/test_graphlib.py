"""Graph embeddings tests.

Mirrors the reference's deeplearning4j-graph test suite
(deeplearning4j-graph/src/test/java/org/deeplearning4j/graph/):
TestGraph.java (structure), TestGraphHuffman.java (coding invariants),
DeepWalkGradientCheck.java / TestDeepWalk.java (embedding quality on
clustered toy graphs, save/load round-trip).
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.graphlib import (
    Graph, Edge, GraphLoader, NoEdgesError, NoEdgeHandling,
    RandomWalkIterator, WeightedRandomWalkIterator, GraphHuffman, DeepWalk,
    GraphVectors,
)


def _two_cluster_graph(k=6):
    """Two complete K_k clusters joined by a single bridge edge."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.add_edge(base + i, base + j)
    g.add_edge(0, k)  # bridge
    return g


# ---------------------------------------------------------------- structure

def test_graph_structure():
    g = Graph(4)
    g.add_edge(0, 1)
    g.add_edge(1, 2, directed=True)
    g.add_edge(Edge(2, 3, value=2.5))
    assert g.num_vertices() == 4
    assert g.num_edges() == 3
    # undirected edge appears in both adjacency lists
    assert 0 in g.get_connected_vertex_indices(1)
    assert 1 in g.get_connected_vertex_indices(0)
    # directed edge only forward
    assert 2 in g.get_connected_vertex_indices(1)
    assert 1 not in g.get_connected_vertex_indices(2)
    assert g.get_vertex_degree(1) == 2


def test_graph_loader_roundtrip(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("# comment\n0 1\n1 2 0.5\n2 3\n")
    g = GraphLoader.load_weighted_edge_list(str(p), 4)
    assert g.num_edges() == 3
    edges = {(e.frm, e.to): e.weight() for e in g.get_edges_out(1) if e.frm == 1}
    assert edges[(1, 2)] == 0.5


# ------------------------------------------------------------------- walks

def test_random_walk_properties():
    g = _two_cluster_graph()
    it = RandomWalkIterator(g, walk_length=8, seed=7)
    walks = list(it)
    assert len(walks) == g.num_vertices()
    starts = sorted(int(w[0]) for w in walks)
    assert starts == list(range(g.num_vertices()))  # one walk per vertex
    for w in walks:
        assert len(w) == 9
        for a, b in zip(w[:-1], w[1:]):
            assert int(b) in g.get_connected_vertex_indices(int(a))


def test_disconnected_vertex_handling():
    g = Graph(3)
    g.add_edge(0, 1)
    it = RandomWalkIterator(g, walk_length=4, seed=1,
                            no_edge_handling=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)
    walks = {int(w[0]): w for w in it}
    assert all(int(v) == 2 for v in walks[2])  # self-loops in place
    it2 = RandomWalkIterator(g, walk_length=4, seed=1,
                             no_edge_handling=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)
    with pytest.raises(NoEdgesError):
        list(it2)


def test_weighted_walk_respects_weights():
    # vertex 0 has a heavy edge to 1 (w=100) and light to 2 (w=1)
    g = Graph(3)
    g.add_edge(0, 1, value=100.0)
    g.add_edge(0, 2, value=1.0)
    g.add_edge(1, 2, value=1.0)
    it = WeightedRandomWalkIterator(g, walk_length=1, seed=3)
    heavy = 0
    n_trials = 200
    for trial in range(n_trials):
        it.seed = trial
        it.reset()
        for w in it:
            if int(w[0]) == 0 and int(w[1]) == 1:
                heavy += 1
    assert heavy > 0.85 * n_trials  # ~99% expected


# ----------------------------------------------------------------- huffman

def test_graph_huffman_invariants():
    g = _two_cluster_graph()
    h = GraphHuffman(g)
    n = g.num_vertices()
    codes = [tuple(h.get_code(i)) for i in range(n)]
    # prefix-free: no code is a prefix of another
    for i in range(n):
        for j in range(n):
            if i != j:
                ci, cj = codes[i], codes[j]
                assert not (len(ci) <= len(cj) and cj[:len(ci)] == ci)
    # higher-degree vertices get codes no longer than lower-degree ones
    degs = g.degree_vector()
    hi, lo = int(np.argmax(degs)), int(np.argmin(degs))
    assert len(codes[hi]) <= len(codes[lo])
    # points are valid inner-node ids
    for i in range(n):
        for p in h.get_path_inner_nodes(i):
            assert 0 <= p < n - 1


# ---------------------------------------------------------------- deepwalk

def test_deepwalk_two_cluster_embedding(tmp_path):
    g = _two_cluster_graph(k=6)
    dw = (DeepWalk.builder().vector_size(16).window_size(3)
          .learning_rate(0.1).seed(42).build())
    dw.initialize(g)
    assert dw.vectors.shape == (12, 16)
    dw.fit(walk_length=8, epochs=50)
    # same-cluster pairs should be closer than cross-cluster pairs
    intra = np.mean([dw.similarity(i, j)
                     for i in range(1, 6) for j in range(i + 1, 6)])
    inter = np.mean([dw.similarity(i, j)
                     for i in range(1, 6) for j in range(7, 12)])
    assert intra > inter + 0.1, (intra, inter)
    # nearest neighbours of a non-bridge vertex stay in its own cluster
    near = dw.vertices_nearest(2, top=3)
    assert sum(1 for v in near if v < 6) >= 2
    # save/load round trip
    p = str(tmp_path / "dw.txt")
    dw.save(p)
    gv = DeepWalk.load(p)
    assert isinstance(gv, GraphVectors)
    np.testing.assert_allclose(gv.vectors, dw.vectors, rtol=1e-4, atol=1e-5)


def test_deepwalk_requires_initialize():
    dw = DeepWalk(vector_size=8)
    with pytest.raises(RuntimeError):
        dw.fit()
