"""Distributed/long-context tests on the 8-device virtual CPU mesh —
the analog of the reference's BaseSparkTest master=local[n] strategy
(SURVEY.md §4.5): multi-worker semantics exercised in-process.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, RnnOutputLayer, SelfAttentionLayer,
                                MultiLayerNetwork, DataSet, ListDataSetIterator,
                                Sgd, Adam, NoOp)
from deeplearning4j_tpu.parallel.sharding import make_mesh, SEQ_AXIS
from deeplearning4j_tpu.parallel import collectives
from deeplearning4j_tpu.parallel.ring_attention import (
    attention_reference, blockwise_attention, ring_attention)
from deeplearning4j_tpu.parallel.cluster import (
    ParameterAveragingTrainingMaster, SparkDl4jMultiLayer,
    ParameterServerParallelWrapper)


# ------------------------------------------------------------- attention

def _qkv(rng, B=2, T=32, H=4, D=8):
    q = rng.normal(size=(B, T, H, D)).astype(np.float32)
    k = rng.normal(size=(B, T, H, D)).astype(np.float32)
    v = rng.normal(size=(B, T, H, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_attention_matches_reference(causal):
    q, k, v = _qkv(np.random.default_rng(0))
    full = attention_reference(q, k, v, causal=causal)
    blk = blockwise_attention(q, k, v, block_size=8, causal=causal)
    np.testing.assert_allclose(np.asarray(full), np.asarray(blk),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    """Ring attention over an 8-way seq-sharded mesh == full attention."""
    mesh = make_mesh(n_data=1, n_seq=8)
    q, k, v = _qkv(np.random.default_rng(1), T=64)
    full = attention_reference(q, k, v, causal=causal)
    ring = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_attention_key_mask_matches_reference(causal):
    """key_mask on the memory-bounded path == the reference oracle,
    including blocks that are FULLY masked for some rows (the online
    softmax's exp(m - m_new) correction must zero their bogus partials)."""
    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng)
    mask = (rng.random((2, 32)) > 0.4).astype(np.float32)
    mask[0, :8] = 0.0      # an entirely-masked leading block (block_size=8)
    mask[:, -1] = 1.0      # every row keeps at least one valid key
    mask = jnp.asarray(mask)
    full = attention_reference(q, k, v, causal=causal, key_mask=mask)
    blk = blockwise_attention(q, k, v, block_size=8, causal=causal,
                              key_mask=mask)
    np.testing.assert_allclose(np.asarray(full), np.asarray(blk),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_key_mask_matches_reference(causal):
    """The key mask shards over the seq axis and rotates around the ring
    with K/V; results must equal full masked attention."""
    mesh = make_mesh(n_data=1, n_seq=8)
    rng = np.random.default_rng(8)
    q, k, v = _qkv(rng, T=64)
    mask = (rng.random((2, 64)) > 0.4).astype(np.float32)
    mask[1, 8:16] = 0.0    # one device's whole shard masked for a row
    mask[:, 0] = 1.0
    mask = jnp.asarray(mask)
    full = attention_reference(q, k, v, causal=causal, key_mask=mask)
    ring = ring_attention(q, k, v, mesh, causal=causal, key_mask=mask)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


def test_self_attention_layer_forward_and_gradcheck():
    from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
    rng = np.random.default_rng(2)
    b, t, nin, nout = 2, 8, 6, 3
    x = rng.normal(size=(b, t, nin))
    y = np.eye(nout)[rng.integers(0, nout, (b, t)).ravel()].reshape(b, t, nout)
    conf = (NeuralNetConfiguration.builder().seed(3).updater(NoOp())
            .dtype("float64").list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, activation="identity"))
            .layer(RnnOutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.recurrent(nin))
            .build())
    net = MultiLayerNetwork(conf).init()
    out = net.output(x)
    assert out.shape == (b, t, nout)
    assert check_gradients(net, x, y, print_results=True)


def test_self_attention_layer_causal_is_causal():
    """With causal=True, output at time t must not depend on inputs after t."""
    rng = np.random.default_rng(3)
    conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.1)).list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True,
                                      activation="identity"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.recurrent(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(1, 10, 5)).astype(np.float32)
    base = np.asarray(net.output(x))
    x2 = x.copy()
    x2[0, 7:] += 10.0  # perturb the future
    pert = np.asarray(net.output(x2))
    np.testing.assert_allclose(base[0, :7], pert[0, :7], rtol=1e-5, atol=1e-6)


def test_self_attention_respects_mask():
    rng = np.random.default_rng(4)
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1)).list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, activation="identity"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.recurrent(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(1, 6, 5)).astype(np.float32)
    mask = np.array([[1, 1, 1, 1, 0, 0]], np.float32)
    feats = net.layers[0].forward(net.params["0"], net.states["0"],
                                  jnp.asarray(x), mask=jnp.asarray(mask))[0]
    x2 = x.copy()
    x2[0, 4:] = 99.0  # change masked positions
    feats2 = net.layers[0].forward(net.params["0"], net.states["0"],
                                   jnp.asarray(x2), mask=jnp.asarray(mask))[0]
    np.testing.assert_allclose(np.asarray(feats[0, :4]),
                               np.asarray(feats2[0, :4]), rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ collectives

def test_collectives_smoke():
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(n_data=8)

    def body(x):
        s = collectives.all_reduce_sum(x, "data")
        m = collectives.all_reduce_mean(x, "data")
        g = collectives.all_gather(x, "data")
        r = collectives.ring_shift(x, "data")
        return s, m, g, r

    x = jnp.arange(8.0).reshape(8, 1)
    fn = shard_map(body, mesh=mesh, in_specs=P("data", None),
                   out_specs=(P("data", None), P("data", None),
                              P("data", None), P("data", None)))
    s, m, g, r = fn(x)
    assert float(s[0, 0]) == 28.0          # sum 0..7 everywhere
    assert float(m[3, 0]) == 3.5
    np.testing.assert_array_equal(np.asarray(r).ravel(),
                                  np.roll(np.arange(8.0), 1))


def test_multi_slice_mesh_fallback():
    mesh = collectives.multi_slice_mesh((2, 4), ("dcn", "data"))
    assert mesh.shape["dcn"] == 2 and mesh.shape["data"] == 4


# --------------------------------------------------------- cluster facade

def _toy(seed=0, n=128):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return x, y


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def test_training_master_allreduce():
    x, y = _toy()
    net = _net()
    tm = (ParameterAveragingTrainingMaster.builder(16)
          .worker_count(8).mode("allreduce").build())
    spark_net = SparkDl4jMultiLayer(None, net, tm)
    s0 = net.score(x, y)
    spark_net.fit(ListDataSetIterator(DataSet(x, y), batch_size=32))
    assert net.score_value < s0


def test_training_master_averaging_matches_allreduce_direction():
    """Averaging-mode training must also learn (the reference's param-averaging
    math); scores comparable to allreduce mode."""
    x, y = _toy()
    net = _net(seed=2)
    s0 = net.score(x, y)
    tm = (ParameterAveragingTrainingMaster.builder(16)
          .worker_count(4).averaging_frequency(2).mode("averaging").build())
    for _ in range(6):
        tm.execute_training(net, ListDataSetIterator(DataSet(x, y), batch_size=16))
    assert net.score(x, y) < s0
    assert np.isfinite(net.score_value)


def test_sharded_trainer_handles_uneven_final_batch():
    """100 samples, batch 32, 8 workers: the final 4-sample batch is not
    divisible by the data axis and must not crash (tail truncated)."""
    from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper
    x, y = _toy(7, n=100)
    net = _net(seed=7)
    pw = ParallelWrapper.builder(net).workers(8).build()
    s0 = net.score(x, y)
    pw.fit(ListDataSetIterator(DataSet(x, y), batch_size=32), epochs=2)
    assert net.score(x, y) < s0


def test_self_attention_masked_outputs_are_zero():
    conf = (NeuralNetConfiguration.builder().seed(8).updater(Sgd(0.1)).list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, activation="identity"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.recurrent(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(9).normal(size=(1, 6, 5)).astype(np.float32)
    mask = np.array([[1, 1, 1, 1, 0, 0]], np.float32)
    feats = net.layers[0].forward(net.params["0"], net.states["0"],
                                  jnp.asarray(x), mask=jnp.asarray(mask))[0]
    np.testing.assert_allclose(np.asarray(feats[0, 4:]), 0.0, atol=1e-7)


def test_parameter_server_facade_delegates():
    x, y = _toy(3)
    net = _net(seed=3)
    pw = ParameterServerParallelWrapper.builder(net).workers(8).build()
    s0 = net.score(x, y)
    pw.fit(ListDataSetIterator(DataSet(x, y), batch_size=32))
    assert net.score(x, y) < s0


def test_training_master_averaging_computation_graph():
    from deeplearning4j_tpu import ComputationGraph
    conf = (NeuralNetConfiguration.builder().seed(6).updater(Sgd(0.1))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    g = ComputationGraph(conf).init()
    x, y = _toy(6)
    s0 = g.score(DataSet(x, y))
    tm = (ParameterAveragingTrainingMaster.builder(16)
          .worker_count(4).mode("averaging").build())
    for _ in range(4):
        tm.execute_training(g, ListDataSetIterator(DataSet(x, y), batch_size=16))
    assert g.score(DataSet(x, y)) < s0


def test_training_master_averaging_passes_masks():
    """Masked recurrent training in averaging mode must honor the masks."""
    from deeplearning4j_tpu import GravesLSTM, RnnOutputLayer
    rng = np.random.default_rng(11)
    b, t = 32, 6
    x = rng.normal(size=(b, t, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (b, t))]
    mask = np.ones((b, t), np.float32)
    mask[:, 4:] = 0
    conf = (NeuralNetConfiguration.builder().seed(12).updater(Sgd(0.05)).list()
            .layer(GravesLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.recurrent(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
    tm = (ParameterAveragingTrainingMaster.builder(8)
          .worker_count(4).mode("averaging").build())
    s0 = net.score(ds)
    for _ in range(3):
        tm.execute_training(net, ListDataSetIterator(ds, batch_size=8))
    assert net.score(ds) < s0


def test_training_master_rebatches_to_worker_batch_size():
    x, y = _toy(8, n=96)
    net = _net(seed=8)
    tm = (ParameterAveragingTrainingMaster.builder(4)   # 4/worker * 8 = 32 global
          .worker_count(8).mode("allreduce").build())
    s0 = net.score(x, y)
    # upstream iterator uses a mismatched batch size; master re-cuts it
    tm.execute_training(net, ListDataSetIterator(DataSet(x, y), batch_size=50))
    assert net.score(x, y) < s0


def test_sharded_trainer_small_batches_still_train():
    """Batches smaller than the data axis are wrap-padded and loss-masked
    rather than skipped — every example trains (VERDICT r2 weak #6)."""
    from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper
    x, y = _toy(9, n=16)
    net = _net(seed=9)
    pw = ParallelWrapper.builder(net).workers(8).build()
    # every batch (4 examples) is smaller than the 8-way data axis
    pw.fit(ListDataSetIterator(DataSet(x, y), batch_size=4))
    assert net.iteration_count == 4  # ceil(16/4) batches all trained
    assert net.examples_fit == 16


def test_early_stopping_parallel_trainer():
    from deeplearning4j_tpu.earlystopping import (
        EarlyStoppingConfiguration, MaxEpochsTerminationCondition,
        DataSetLossCalculator)
    from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingParallelTrainer
    x, y = _toy(4)
    net = _net(seed=4)
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .score_calculator(DataSetLossCalculator(
               ListDataSetIterator(DataSet(x, y), batch_size=32)))
           .build())
    trainer = EarlyStoppingParallelTrainer(
        cfg, net, ListDataSetIterator(DataSet(x, y), batch_size=32), workers=8)
    result = trainer.fit()
    assert result.total_epochs == 3
    assert result.get_best_model() is not None


def test_training_master_averaging_multi_input_graph():
    """Averaging mode on a multi-input/multi-output ComputationGraph
    (previously NotImplementedError; reference ParameterAveragingTrainingMaster
    handles MultiDataSet via SparkComputationGraph)."""
    from deeplearning4j_tpu import (ComputationGraph, MergeVertex, MultiDataSet)
    rng = np.random.default_rng(5)
    Xa = rng.normal(size=(64, 4)).astype(np.float32)
    Xb = rng.normal(size=(64, 3)).astype(np.float32)
    w = rng.normal(size=(7, 2))
    Y = np.eye(2, dtype=np.float32)[np.argmax(np.concatenate([Xa, Xb], 1) @ w, axis=1)]
    conf = (NeuralNetConfiguration.builder()
            .seed(11).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("a", "b")
            .add_vertex("merged", MergeVertex(), "a", "b")
            .add_layer("d", DenseLayer(n_out=16, activation="relu"), "merged")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4), InputType.feed_forward(3))
            .build())
    g = ComputationGraph(conf).init()
    s0 = g.score(MultiDataSet([Xa, Xb], [Y]))
    data = [MultiDataSet([Xa[i:i + 16], Xb[i:i + 16]], [Y[i:i + 16]])
            for i in range(0, 64, 16)]
    tm = (ParameterAveragingTrainingMaster.builder(16)
          .worker_count(4).averaging_frequency(1).mode("averaging").build())
    for _ in range(20):
        tm.execute_training(g, data)
    assert g.score(MultiDataSet([Xa, Xb], [Y])) < s0 * 0.7


def test_ring_attention_gradients_match_reference():
    """Ring attention must be differentiable with gradients matching full
    attention — sequence-parallel TRAINING, not just inference."""
    mesh = make_mesh(n_data=1, n_model=1, n_seq=8)
    q, k, v = _qkv(np.random.default_rng(4), H=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("key_mask", [False, True])
def test_ring_attention_runs_flash_kernel(key_mask):
    """VERDICT r4 #4: the per-ring-step update must be the Pallas flash
    kernel (per visiting shard, global key offset driving the causal mask),
    not the materializing einsum — proven by counting kernel invocations —
    and the flash and einsum ring paths must agree with the reference."""
    import importlib
    fa = importlib.import_module("deeplearning4j_tpu.kernels.flash_attention")
    mesh = make_mesh(n_data=1, n_seq=8)
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, T=64)
    mask = None
    if key_mask:
        m = (rng.random((2, 64)) > 0.4).astype(np.float32)
        m[:, 0] = 1.0
        mask = jnp.asarray(m)

    calls = []
    orig = fa._flash_forward
    fa._flash_forward = lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1]
    try:
        ring = ring_attention(q, k, v, mesh, causal=True, key_mask=mask)
    finally:
        fa._flash_forward = orig
    assert calls, "ring attention never invoked the flash kernel"
    full = attention_reference(q, k, v, causal=True, key_mask=mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                               rtol=2e-4, atol=2e-5)
    # einsum fallback (use_flash=False) stays available and agrees
    ring_e = ring_attention(q, k, v, mesh, causal=True, key_mask=mask,
                            use_flash=False)
    np.testing.assert_allclose(np.asarray(ring_e), np.asarray(full),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_flash_gradients_match_reference():
    """Training through the flash-in-ring path: gradients must match full
    attention (the per-step custom VJP + the log-sum-exp merge, including
    the LSE cotangent's fold into the delta term)."""
    mesh = make_mesh(n_data=1, n_seq=8)
    q, k, v = _qkv(np.random.default_rng(6), T=64, H=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True,
                                      use_flash=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
