"""Production serving subsystem tests: dynamic micro-batching (padded
power-of-two buckets, zero steady-state recompiles), versioned registry
hot-swap, admission control (deadlines, 429 shedding, graceful drain), and
metrics routing into the ui/storage stats tier."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd,
                                ModelSerializer)
from deeplearning4j_tpu.serving import (AdmissionQueue, DeadlineExceeded,
                                        DynamicBatcher, ModelRegistry,
                                        RejectedError, ServingMetrics,
                                        ServingServer, bucket_for)
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def _net(nin=6, nout=3, seed=0):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())
    return MultiLayerNetwork(conf).init()


class StubModel:
    """Duck-typed model: deterministic affine output + optional dispatch
    delay, to exercise batching/swap/deadline logic without XLA compiles."""

    def __init__(self, scale, delay_s=0.0):
        self.scale = float(scale)
        self.delay_s = float(delay_s)

    def output(self, x):
        if self.delay_s:
            time.sleep(self.delay_s)
        return np.asarray(x) * self.scale


def _component_server(model, **kw):
    """ServingServer with only the batcher running (no HTTP socket)."""
    server = ServingServer(model, **kw)
    server.batcher.start()
    return server


def _wait_queue_empty(server, timeout=5.0):
    deadline = time.monotonic() + timeout
    while server.queue.depth() > 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert server.queue.depth() == 0


# --------------------------------------------------------------- batching

def test_bucket_for_powers_of_two():
    assert [bucket_for(n) for n in (1, 2, 3, 4, 5, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 16]


def test_batched_predict_bitwise_identical_to_direct_output():
    """Acceptance: batched /predict == direct model.output, bitwise."""
    net = _net()
    server = ServingServer(net, port=0).start()
    rng = np.random.default_rng(0)
    try:
        for rows in (4, 3, 1, 2):
            x = rng.normal(size=(rows, 6)).astype(np.float32)
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": x.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.loads(r.read())
            direct = np.asarray(net.output(x))
            np.testing.assert_array_equal(
                np.asarray(out["prediction"], dtype=direct.dtype), direct)
            assert out["shape"] == [rows, 3]
            assert out["version"] == "v1"
    finally:
        server.stop()


def test_legacy_1d_body_served_as_single_example():
    """A flat-vector body (legacy single example) must be lifted to a 1-row
    batch — not padded/chunked along the feature axis — and answered with
    the un-batched shape, as the old InferenceServer did."""
    net = _net()
    server = ServingServer(net, port=0).start()
    rng = np.random.default_rng(7)
    x1d = rng.normal(size=(6,)).astype(np.float32)
    try:
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x1d.tolist()}).encode())
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["shape"] == [3]
        np.testing.assert_allclose(out["prediction"],
                                   np.asarray(net.output(x1d)),
                                   rtol=1e-6, atol=1e-7)
    finally:
        server.stop()


def test_legacy_wrapper_public_attributes():
    """The compat wrapper keeps the old public surface: `.model` and an
    assignable `.served` counter."""
    from deeplearning4j_tpu.streaming import InferenceServer
    net = _net()
    server = InferenceServer(net, port=0).start()
    try:
        assert server.model is net
        server.predict(np.ones((2, 6), dtype=np.float32))
        assert server.served == 2
        server.served = 0                      # legacy reset still works
        assert server.served == 0
        server.predict(np.ones((3, 6), dtype=np.float32))
        assert server.served == 3
        # legacy hot-swap idiom: assigning .model must change what serves
        net2 = _net(seed=1)
        server.model = net2
        assert server.model is net2
        x = np.ones((2, 6), dtype=np.float32)
        np.testing.assert_array_equal(
            server.predict(x)["prediction"], np.asarray(net2.output(x)))
        # ...without leaking old versions (repeated assignment = one model)
        for _ in range(3):
            server.model = _net(seed=2)
        assert len(server.registry.versions()) == 1
    finally:
        server.stop()


def test_stop_start_cycle_resumes_serving():
    """stop()/start() (maintenance pause) must come back actually serving,
    not shedding everything with 429 off a permanently closed queue."""
    net = _net()
    server = ServingServer(net, port=0).start()
    x = np.ones((2, 6), dtype=np.float32)
    first = server.predict(x)
    observed_before = set(server.batcher.observed)
    server.stop()
    server.start()
    try:
        # observed buckets survive the restart so deploy warm-up still
        # covers pre-restart traffic shapes
        assert server.batcher.observed == observed_before != set()
        again = server.predict(x)
        np.testing.assert_array_equal(again["prediction"],
                                      first["prediction"])
    finally:
        server.stop()


def test_abandon_cancels_lifted_and_chunked_work():
    """_abandon (the 503 path) must free queue capacity for 1-D lifted and
    chunked requests, not just cancel the outer wrapper future."""
    server = _component_server(StubModel(2.0, delay_s=0.3),
                               queue_capacity=1, max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        busy = server.submit(x)
        _wait_queue_empty(server)
        time.sleep(0.05)
        lifted = server.submit(np.ones(4, dtype=np.float32))  # fills queue
        server._abandon(lifted)
        live = server.submit(x)         # capacity freed: admitted, not 429
        busy.result(timeout=10)
        np.testing.assert_array_equal(live.result(timeout=10)["prediction"],
                                      x * 2.0)
        time.sleep(0.1)
        assert server.metrics.rows.get() == 2   # abandoned row never served
    finally:
        server.stop()


def test_transform_applied_exactly_once_for_1d_input():
    """The 1-D lift must not re-apply the transform (legacy semantics:
    transform runs once on the raw input)."""
    server = _component_server(StubModel(1.0), max_latency_ms=1.0,
                               transform=lambda x: x + 1.0)
    try:
        res = server.submit(np.zeros(4, dtype=np.float32)).result(timeout=10)
        np.testing.assert_array_equal(res["prediction"],
                                      np.ones(4, dtype=np.float32))
    finally:
        server.stop()


def test_zero_recompiles_mixed_sizes_within_bucket():
    """Acceptance: a steady-state mixed-size workload compiles at most one
    executable per shape bucket (counted via the jit cache)."""
    net = _net()
    server = _component_server(net, max_latency_ms=1.0)
    rng = np.random.default_rng(1)
    try:
        # warm one bucket: sizes 3 and 4 both pad to bucket 4
        for rows in (3, 4):
            server.predict(rng.normal(size=(rows, 6)).astype(np.float32))
        jitted = net._jit_cache[("output", False, False)]
        assert jitted._cache_size() == 1      # ONE executable for the bucket
        for _ in range(20):                    # steady state: zero recompiles
            rows = int(rng.integers(3, 5))
            server.predict(rng.normal(size=(rows, 6)).astype(np.float32))
        assert jitted._cache_size() == 1
        # new bucket sizes compile exactly one executable each
        for rows in (1, 2):
            server.predict(rng.normal(size=(rows, 6)).astype(np.float32))
        assert jitted._cache_size() == 3      # buckets {1, 2, 4}
        for _ in range(20):
            rows = int(rng.integers(1, 5))
            server.predict(rng.normal(size=(rows, 6)).astype(np.float32))
        assert jitted._cache_size() == 3
        hist = server.metrics.snapshot()["batch_size_histogram"]
        assert set(hist) <= {"1", "2", "4"}
    finally:
        server.stop()


def test_concurrent_requests_coalesce_into_batches():
    """Concurrent submits within the latency window share a dispatch."""
    server = _component_server(StubModel(2.0, delay_s=0.05),
                               max_batch_size=8, max_latency_ms=100.0)
    try:
        xs = [np.full((2, 4), float(i + 1), dtype=np.float32)
              for i in range(4)]
        futs = [server.submit(x) for x in xs]
        for x, fut in zip(xs, futs):
            res = fut.result(timeout=10)
            np.testing.assert_array_equal(res["prediction"], x * 2.0)
        snap = server.metrics.snapshot()
        assert snap["requests"] == 4 and snap["rows"] == 8
        assert snap["batches"] < 4            # at least one coalesced batch
    finally:
        server.stop()


# ----------------------------------------------------- admission control

def test_deadline_expiry_fails_exactly_the_expired_caller():
    server = _component_server(StubModel(2.0, delay_s=0.3),
                               max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        f1 = server.submit(x)                  # occupies the batcher ~300ms
        _wait_queue_empty(server)
        time.sleep(0.05)                       # f1's coalescing window closed
        f2 = server.submit(x, timeout_ms=50)   # expires while queued
        f3 = server.submit(x * 3)              # no deadline: must survive
        with pytest.raises(DeadlineExceeded):
            f2.result(timeout=10)
        np.testing.assert_array_equal(f1.result(timeout=10)["prediction"],
                                      x * 2.0)
        np.testing.assert_array_equal(f3.result(timeout=10)["prediction"],
                                      x * 6.0)
        assert server.metrics.expired.get() == 1
        assert server.metrics.requests.get() == 2
    finally:
        server.stop()


def test_full_queue_sheds_immediately():
    server = _component_server(StubModel(1.0, delay_s=0.5),
                               queue_capacity=2, max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        first = server.submit(x)               # taken by the batcher
        _wait_queue_empty(server)
        time.sleep(0.05)                       # its coalescing window closed
        queued = [server.submit(x) for _ in range(2)]   # fills the queue
        t0 = time.monotonic()
        with pytest.raises(RejectedError) as exc:
            server.submit(x)
        assert time.monotonic() - t0 < 0.1     # shed decision, not a hang
        assert exc.value.retry_after_s >= 1
        assert server.metrics.shed.get() == 1
        for f in [first] + queued:             # admitted work still completes
            f.result(timeout=10)
    finally:
        server.stop()


def test_expired_queue_entries_dont_cause_false_429():
    """Requests that expired while queued are dead weight: they must not
    count against capacity and shed live traffic off an idle queue."""
    server = _component_server(StubModel(2.0, delay_s=0.4),
                               queue_capacity=2, max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        busy = server.submit(x)                # occupy the batcher ~400ms
        _wait_queue_empty(server)
        time.sleep(0.05)
        dead = [server.submit(x, timeout_ms=10) for _ in range(2)]  # fills it
        time.sleep(0.05)                       # both now expired in queue
        live = server.submit(x)                # must purge + admit, not 429
        for f in dead:
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=10)
        np.testing.assert_array_equal(busy.result(timeout=10)["prediction"],
                                      x * 2.0)
        np.testing.assert_array_equal(live.result(timeout=10)["prediction"],
                                      x * 2.0)
        assert server.metrics.shed.get() == 0
    finally:
        server.stop()


def test_http_429_with_retry_after_not_a_hang():
    """Acceptance: a full queue yields HTTP 429 (not a hang)."""
    # max_batch_size=1: every dispatch is a 0.2s single-request batch, so
    # with capacity 1 the later concurrent posts must shed deterministically
    server = ServingServer(StubModel(2.0, delay_s=0.2), port=0,
                           queue_capacity=1, max_batch_size=1,
                           max_latency_ms=1.0).start()
    try:
        body = json.dumps({"data": [[1.0, 2.0]]}).encode()

        def fire(results, i):
            req = urllib.request.Request(server.url + "/predict", data=body)
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    results[i] = (r.status, None)
            except urllib.error.HTTPError as e:
                results[i] = (e.code, e.headers.get("Retry-After"))

        results = {}
        threads = [threading.Thread(target=fire, args=(results, i))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        codes = [c for c, _ in results.values()]
        assert len(codes) == 6                 # nothing hung
        assert codes.count(200) >= 1
        assert codes.count(429) >= 1
        retry_after = [ra for c, ra in results.values() if c == 429]
        assert all(ra is not None for ra in retry_after)
    finally:
        server.stop()


def test_client_cancelled_future_does_not_kill_batcher():
    """A caller may cancel() the future from submit(); completing a cancelled
    future raises InvalidStateError, which must be swallowed — not kill the
    batcher thread or fail innocent same-batch requests."""
    server = _component_server(StubModel(2.0, delay_s=0.1),
                               max_batch_size=8, max_latency_ms=50.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        f1 = server.submit(x)
        assert f1.cancel()                     # cancelled while queued
        f2 = server.submit(x)                  # coalesces with cancelled f1
        np.testing.assert_array_equal(f2.result(timeout=10)["prediction"],
                                      x * 2.0)
        # cancelled + expired path must not kill the batcher either
        f3 = server.submit(x, timeout_ms=1)
        f3.cancel()
        time.sleep(0.05)
        f4 = server.submit(x)
        np.testing.assert_array_equal(f4.result(timeout=10)["prediction"],
                                      x * 2.0)
    finally:
        server.stop()


def test_graceful_drain_on_stop():
    server = _component_server(StubModel(2.0, delay_s=0.05),
                               max_latency_ms=1.0)
    x = np.ones((1, 4), dtype=np.float32)
    futs = [server.submit(x) for _ in range(4)]
    server.stop(drain=True)
    for f in futs:                             # nothing dropped on shutdown
        np.testing.assert_array_equal(f.result(timeout=1)["prediction"],
                                      x * 2.0)
    with pytest.raises(RejectedError, match="draining"):
        server.submit(x)


def test_oversized_request_chunked_into_bounded_buckets():
    """A request larger than max_batch_size is served by transparent
    server-side chunking (legacy clients may send any batch size) WITHOUT
    minting buckets past the log2(max_batch_size)+1 bound."""
    server = _component_server(StubModel(2.0), max_batch_size=8,
                               max_latency_ms=1.0)
    try:
        x = np.arange(100 * 4, dtype=np.float32).reshape(100, 4)
        res = server.submit(x).result(timeout=10)
        np.testing.assert_array_equal(res["prediction"], x * 2.0)  # in order
        assert all(bucket <= 8 for _, bucket in server.batcher.observed)
        assert server.metrics.rows.get() == 100
        assert server.metrics.requests.get() == 1  # one client call, not 13
    finally:
        server.stop()


def test_predict_before_any_deploy_fails_batch_not_batcher():
    """No model deployed: the request's future fails, the batcher thread
    survives, and serving recovers after a deploy."""
    registry = ModelRegistry()
    server = _component_server(None, registry=registry, max_latency_ms=1.0)
    try:
        fut = server.submit(np.ones((1, 4), dtype=np.float32))
        with pytest.raises(RuntimeError, match="no model deployed"):
            fut.result(timeout=10)
        assert server.metrics.errors.get() == 1
        registry.register("v1", StubModel(2.0))
        server.deploy("v1")                        # batcher must still be alive
        res = server.predict(np.ones((1, 4), dtype=np.float32), wait_s=10)
        np.testing.assert_array_equal(res["prediction"], [[2.0, 2.0, 2.0, 2.0]])
    finally:
        server.stop()


def test_short_deadline_not_held_for_full_coalescing_window():
    """timeout_ms shorter than max_latency_ms: the coalescing window is cut
    to the request's deadline, so it dispatches on time instead of being
    held the full window (let alone expiring)."""
    server = _component_server(StubModel(2.0), max_latency_ms=2000.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        t0 = time.monotonic()
        res = server.submit(x, timeout_ms=100).result(timeout=10)
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(res["prediction"], x * 2.0)
        assert elapsed < 1.0, f"held {elapsed:.2f}s by the 2s window"
        assert server.metrics.expired.get() == 0
    finally:
        server.stop()


def test_malformed_request_does_not_poison_deploy_warmup():
    """A wrong-feature-count request fails its own caller (400 path) but must
    not enter the observed-bucket set, or every later deploy/rollback would
    replay it and fail."""
    net1, net2 = _net(seed=0), _net(seed=1)
    registry = ModelRegistry()
    registry.register("v1", net1)
    registry.register("v2", net2)
    registry.deploy("v1")
    server = _component_server(None, registry=registry, max_latency_ms=1.0)
    rng = np.random.default_rng(5)
    try:
        good = rng.normal(size=(2, 6)).astype(np.float32)
        server.predict(good)
        bad = server.submit(rng.normal(size=(1, 999)).astype(np.float32))
        with pytest.raises(Exception):
            bad.result(timeout=10)
        assert all(sig != ((999,), "float32")
                   for sig, _ in server.batcher.observed)
        server.deploy("v2")                     # must not replay the bad shape
        assert server.predict(good)["version"] == "v2"
    finally:
        server.stop()


# ------------------------------------------------------ registry hot-swap

def test_hot_swap_mid_traffic_never_drops_or_mixes_versions():
    """Acceptance: hot-swap serves the new version without dropping in-flight
    requests, and no response mixes versions (v1 => x*2, v2 => x*3)."""
    registry = ModelRegistry()
    registry.register("v1", StubModel(2.0, delay_s=0.01))
    registry.register("v2", StubModel(3.0, delay_s=0.01))
    registry.deploy("v1")
    server = _component_server(None, registry=registry, max_batch_size=8,
                               max_latency_ms=2.0)
    results, errors = [], []
    lock = threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        for _ in range(15):
            x = rng.normal(size=(int(rng.integers(1, 4)), 4)) \
                   .astype(np.float32)
            try:
                res = server.submit(x).result(timeout=10)
                with lock:
                    results.append((x, res))
            except Exception as e:
                with lock:
                    errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        prev = server.deploy("v2")             # atomic swap mid-traffic
        assert prev == "v1"
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(results) == 60              # zero drops
        scale = {"v1": 2.0, "v2": 3.0}
        seen = set()
        for x, res in results:
            seen.add(res["version"])
            np.testing.assert_array_equal(res["prediction"],
                                          x * scale[res["version"]])
        assert seen == {"v1", "v2"}            # traffic straddled the swap
        counts = {v["version"]: v["serve_count"]
                  for v in registry.versions()}
        assert counts["v1"] > 0 and counts["v2"] > 0
        assert sum(counts.values()) == sum(x.shape[0] for x, _ in results)
    finally:
        server.stop()


def test_deploy_warmup_precompiles_observed_buckets():
    """The incoming version is warm-compiled on every observed bucket BEFORE
    the swap, so steady state on the new version triggers zero recompiles."""
    net1, net2 = _net(seed=0), _net(seed=1)
    registry = ModelRegistry()
    registry.register("v1", net1)
    registry.register("v2", net2)
    registry.deploy("v1")
    server = _component_server(None, registry=registry, max_latency_ms=1.0)
    rng = np.random.default_rng(3)
    try:
        for rows in (3, 4, 2):
            server.predict(rng.normal(size=(rows, 6)).astype(np.float32))
        server.deploy("v2")                    # warms buckets {2, 4} on net2
        jitted2 = net2._jit_cache[("output", False, False)]
        warmed = jitted2._cache_size()
        assert warmed == 2
        for _ in range(10):
            rows = int(rng.integers(2, 5))
            res = server.predict(
                rng.normal(size=(rows, 6)).astype(np.float32))
            assert res["version"] == "v2"
        assert jitted2._cache_size() == warmed  # zero post-swap recompiles
    finally:
        server.stop()


def test_registry_zip_load_deploy_rollback_over_http(tmp_path):
    net1, net2 = _net(seed=0), _net(seed=1)
    zip_path = str(tmp_path / "v2.zip")
    ModelSerializer.write_model(net2, zip_path)
    server = ServingServer(net1, port=0).start()
    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, 6)).astype(np.float32)

    def predict():
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x.tolist()}).encode())
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def post(path, body):
        req = urllib.request.Request(server.url + path,
                                     data=json.dumps(body).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    try:
        out1 = predict()
        assert out1["version"] == "v1"
        d = post("/deploy", {"version": "v2", "path": zip_path})
        assert d == {"active": "v2", "previous": "v1"}
        out2 = predict()
        assert out2["version"] == "v2"
        np.testing.assert_allclose(out2["prediction"],
                                   np.asarray(net2.output(x)),
                                   rtol=1e-6, atol=1e-7)
        with urllib.request.urlopen(server.url + "/models", timeout=10) as r:
            models = json.loads(r.read())
        assert models["active"] == "v2"
        by_v = {m["version"]: m for m in models["models"]}
        assert set(by_v) == {"v1", "v2"}
        assert by_v["v2"]["active"] and not by_v["v1"]["active"]
        assert by_v["v2"]["path"] == zip_path
        assert by_v["v2"]["format"]["model_class"] == "MultiLayerNetwork"
        assert by_v["v1"]["serve_count"] == 2
        r = post("/rollback", {})
        assert r == {"active": "v1"}
        out3 = predict()
        assert out3["version"] == "v1"
        np.testing.assert_array_equal(out3["prediction"], out1["prediction"])
    finally:
        server.stop()


def test_failed_rollback_warmup_keeps_target_retryable():
    """A warm-up failure during rollback must leave BOTH the active version
    and the rollback target intact, so the rollback can be retried."""
    registry = ModelRegistry()
    registry.register("v1", StubModel(2.0))
    registry.register("v2", StubModel(3.0))
    registry.deploy("v1")
    registry.deploy("v2")

    def bad_warmup(model):
        raise RuntimeError("transient warmup failure")

    with pytest.raises(RuntimeError, match="transient"):
        registry.rollback(warmup=bad_warmup)
    assert registry.active_version == "v2"     # unchanged
    assert registry.rollback() == "v1"         # retry succeeds
    assert registry.active_version == "v1"


def test_metrics_scrape_is_rate_limited_to_router():
    """GET /metrics must not append one routed report per scrape."""
    router = InMemoryStatsStorage()
    server = ServingServer(StubModel(2.0), port=0, stats_router=router,
                           session_id="scrape", router_interval_s=60.0).start()
    try:
        for _ in range(5):
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=10) as r:
                r.read()
        assert len(router.get_all_updates("scrape")) == 1   # gated
    finally:
        server.stop()
    # final flush on stop() is unconditional
    assert len(router.get_all_updates("scrape")) == 2


def test_chunked_request_admission_is_all_or_nothing():
    """An oversized request whose chunks don't currently fit the queue sheds
    cleanly (no partial chunks dispatched for a caller that got 429), and one
    that can NEVER fit is a permanent client error, not an eternal 429."""
    server = _component_server(StubModel(2.0, delay_s=0.3),
                               queue_capacity=3, max_batch_size=2,
                               max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        busy = server.submit(x)                # occupy the batcher
        _wait_queue_empty(server)
        time.sleep(0.05)
        queued = [server.submit(x) for _ in range(2)]     # depth 2 of 3
        with pytest.raises(RejectedError):     # 6 rows = 3 chunks; 2+3 > 3
            server.submit(np.ones((6, 4), dtype=np.float32))
        assert server.queue.depth() == 2       # nothing partially admitted
        # more chunks than capacity can never fit: permanent client error,
        # not a retryable 429 against an (eventually) empty queue
        with pytest.raises(ValueError, match="capacity"):
            server.submit(np.ones((8, 4), dtype=np.float32))
        for f in [busy] + queued:
            f.result(timeout=10)
        assert server.metrics.rows.get() == 3  # no orphan chunk dispatches
    finally:
        server.stop()


def test_expired_chunked_request_does_not_deadlock_batcher():
    """Expiring a chunked request's sibling runs its done-callback (which
    withdraws the other chunks) from inside the admission queue — this must
    not deadlock the batcher thread."""
    server = _component_server(StubModel(2.0, delay_s=0.3),
                               max_batch_size=2, max_latency_ms=1.0)
    try:
        x = np.ones((1, 4), dtype=np.float32)
        busy = server.submit(x)                # occupy the batcher ~300ms
        _wait_queue_empty(server)
        time.sleep(0.05)
        big = server.submit(np.ones((6, 4), dtype=np.float32),
                            timeout_ms=50)     # 3 chunks, expire while queued
        with pytest.raises(DeadlineExceeded):
            big.result(timeout=10)
        busy.result(timeout=10)
        ok = server.submit(x).result(timeout=10)   # batcher still alive
        np.testing.assert_array_equal(ok["prediction"], x * 2.0)
    finally:
        server.stop()


def test_failed_path_deploy_is_retryable(tmp_path):
    """/deploy {version, path} whose warm-up fails must roll the registration
    back so the identical request can be retried (not 'already registered')."""
    wide = _net(nin=6)
    narrow = _net(nin=4)                       # wrong width for the traffic
    bad_zip, good_zip = str(tmp_path / "bad.zip"), str(tmp_path / "good.zip")
    ModelSerializer.write_model(narrow, bad_zip)
    ModelSerializer.write_model(_net(nin=6, seed=1), good_zip)
    server = _component_server(wide, max_latency_ms=1.0)
    rng = np.random.default_rng(6)
    try:
        x = rng.normal(size=(2, 6)).astype(np.float32)
        server.predict(x)                      # observe bucket (2, (6,))
        with pytest.raises(Exception):         # warm-up on (2, 6) must fail
            server.deploy("v2", path=bad_zip)
        assert server.registry.active_version == "v1"
        server.deploy("v2", path=good_zip)     # same version id, retried OK
        assert server.predict(x)["version"] == "v2"
    finally:
        server.stop()


def test_deploy_unknown_version_is_400_and_keeps_serving():
    server = ServingServer(StubModel(2.0), port=0).start()
    try:
        req = urllib.request.Request(
            server.url + "/deploy",
            data=json.dumps({"version": "nope"}).encode())
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": [[1.0]]}).encode())
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["prediction"] == [[2.0]]
    finally:
        server.stop()


# ------------------------------------------------------------- metrics/ui

def test_no_model_deployed_is_503_over_http():
    """A deploy gap is a server condition: /predict must answer 503 (load
    balancers retry 5xx), not blame the client with a 400."""
    server = ServingServer(None, registry=ModelRegistry(), port=0,
                           max_latency_ms=1.0).start()
    try:
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": [[1.0, 2.0]]}).encode())
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 503
    finally:
        server.stop()


def test_metrics_endpoint_and_stats_router():
    router = InMemoryStatsStorage()
    server = ServingServer(StubModel(2.0), port=0, stats_router=router,
                           session_id="serve-test").start()
    try:
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": [[1.0, 2.0]]}).encode())
        with urllib.request.urlopen(req, timeout=10) as r:
            r.read()
        with urllib.request.urlopen(server.url + "/metrics", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["requests"] == 1 and snap["rows"] == 1
        assert snap["latency_ms"]["p50"] is not None
        assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"]
        assert snap["batch_size_histogram"] == {"1": 1}
        assert snap["version_rows"] == {"v1": 1}   # from the registry counts
        updates = router.get_all_updates("serve-test")
        assert updates and updates[-1]["type"] == "serving"
        assert updates[-1]["requests"] == 1
    finally:
        server.stop()
    # stop() flushes a final snapshot too
    assert router.get_all_updates("serve-test")[-1]["requests"] == 1


def test_legacy_model_swap_to_different_input_width():
    """The legacy plain-attribute swap allowed replacing the model with one
    of a different input width; the wrapper must deploy it (cold, with stale
    buckets forgotten) instead of failing the assignment on warm-up."""
    from deeplearning4j_tpu.streaming import InferenceServer
    server = InferenceServer(_net(nin=6), port=0).start()
    try:
        server.predict(np.ones((2, 6), dtype=np.float32))   # observe (6,)
        narrow = _net(nin=4, seed=1)
        server.model = narrow                               # width change
        x4 = np.ones((2, 4), dtype=np.float32)
        np.testing.assert_array_equal(
            server.predict(x4)["prediction"], np.asarray(narrow.output(x4)))
        assert len(server.registry.versions()) == 1         # still no leak
    finally:
        server.stop()


def test_file_storage_write_after_close_is_counted_not_raised():
    from deeplearning4j_tpu.ui.storage import FileStatsStorage
    import tempfile, warnings
    store = FileStatsStorage(tempfile.mktemp(suffix=".jsonl"))
    store.put_update({"session_id": "s", "type": "stats", "score": 1.0})
    store.close()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        store.put_update({"session_id": "s", "type": "stats", "score": 2.0})
    assert store.dropped_writes == 1          # divergence surfaced
    assert len(store.get_all_updates("s")) == 2   # memory still consistent


# ------------------------------------------------------------ smoke tests

def test_smoke_serving_light():
    import tools.smoke_serving as smoke
    summary = smoke.run(n_requests=30, concurrency=8, p99_budget_ms=30000.0)
    assert summary["errors"] == [] and summary["shed"] == 0


@pytest.mark.slow
def test_smoke_serving_heavy():
    """Heavy variant of tools/smoke_serving.py: 200 concurrent requests,
    p99 latency budget, zero errors."""
    import tools.smoke_serving as smoke
    summary = smoke.run(n_requests=200, concurrency=16,
                        p99_budget_ms=10000.0)
    assert summary["errors"] == [] and summary["shed"] == 0


# ------------------------------------------------ persistent registry (ETL)

def test_registry_scan_dir_loads_zips_and_deploys_by_name(tmp_path):
    """ModelRegistry(scan_dir=...) loads every ModelSerializer zip at
    startup (version = file stem), and deploy() falls back to
    <scan_dir>/<name>.zip for names registered after startup — the
    persistent-registry ROADMAP item."""
    net_a, net_b = _net(seed=0), _net(seed=1)
    ModelSerializer.write_model(net_a, str(tmp_path / "alpha.zip"))
    ModelSerializer.write_model(net_b, str(tmp_path / "beta.zip"))
    registry = ModelRegistry(scan_dir=str(tmp_path))
    assert {v["version"] for v in registry.versions()} == {"alpha", "beta"}

    registry.deploy("alpha")
    assert registry.active_version == "alpha"
    # a zip dropped into the directory AFTER startup deploys by bare name
    net_c = _net(seed=2)
    ModelSerializer.write_model(net_c, str(tmp_path / "gamma.zip"))
    registry.deploy("gamma")
    assert registry.active_version == "gamma"
    x = np.random.default_rng(0).normal(size=(2, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(registry.active()[1].output(x)),
        np.asarray(net_c.output(x)), rtol=1e-6)
    # unknown names (no zip either) still fail loudly
    with pytest.raises(KeyError):
        registry.deploy("missing")
    # rescan registers without deploying
    ModelSerializer.write_model(_net(seed=3), str(tmp_path / "delta.zip"))
    assert registry.scan() == ["delta"]
    assert registry.active_version == "gamma"


def test_serving_server_scan_dir_deploy_by_name_over_http(tmp_path):
    ModelSerializer.write_model(_net(seed=5), str(tmp_path / "m1.zip"))
    server = ServingServer(scan_dir=str(tmp_path), port=0).start()
    try:
        req = urllib.request.Request(
            server.url + "/deploy",
            data=json.dumps({"version": "m1"}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["active"] == "m1"
        x = np.zeros((1, 6), np.float32)
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x.tolist()}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["version"] == "m1"
    finally:
        server.stop()


def test_zip_normalizer_auto_applied_on_predict(tmp_path):
    """Acceptance (ETL): a normalizer saved in the model zip is auto-applied
    by ServingServer /predict — raw client features, normalized model
    inputs, identical preprocessing to training."""
    from deeplearning4j_tpu import NormalizerStandardize
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(7)
    raw = rng.normal(50.0, 20.0, size=(64, 6)).astype(np.float32)
    nz = NormalizerStandardize().fit(DataSet(raw, raw))
    net = _net(seed=0)
    zip_path = str(tmp_path / "norm.zip")
    ModelSerializer.write_model(net, zip_path, normalizer=nz)

    registry = ModelRegistry()
    registry.load("v1", zip_path)
    assert registry.get("v1").info()["normalizer"] == "NormalizerStandardize"
    server = ServingServer(registry=registry, port=0).start()
    try:
        server.deploy("v1")
        x = raw[:3]
        res = server.predict(x)
        expected = np.asarray(net.output(nz.transform_features(x)))
        np.testing.assert_allclose(res["prediction"], expected,
                                   rtol=1e-5, atol=1e-6)
        # and NOT the un-normalized forward
        assert not np.allclose(res["prediction"], np.asarray(net.output(x)),
                               atol=1e-3)
        # HTTP path agrees with the programmatic path
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x.tolist()}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            http_out = json.loads(r.read())["prediction"]
        np.testing.assert_allclose(http_out, expected, rtol=1e-4, atol=1e-5)
    finally:
        server.stop()


def test_hot_swap_cannot_mix_model_and_normalizer(tmp_path):
    """The batcher dispatches against ONE ModelVersion snapshot: version A's
    model can never run with version B's normalizer mid-swap."""
    from deeplearning4j_tpu import NormalizerMinMaxScaler
    from deeplearning4j_tpu.datasets.dataset import DataSet
    x = np.linspace(0.0, 10.0, 60, dtype=np.float32).reshape(10, 6)
    nz = NormalizerMinMaxScaler().fit(DataSet(x, x))
    net = _net(seed=0)
    p1 = str(tmp_path / "n1.zip")
    ModelSerializer.write_model(net, p1, normalizer=nz)
    registry = ModelRegistry()
    registry.load("v1", p1)
    registry.register("v2", StubModel(1.0))       # no normalizer at all
    server = _component_server(None, registry=registry)
    try:
        registry.deploy("v1")
        out1 = server.predict(x[:2])["prediction"]
        np.testing.assert_allclose(
            out1, np.asarray(net.output(nz.transform_features(x[:2]))),
            rtol=1e-5, atol=1e-6)
        registry.deploy("v2")
        out2 = server.predict(x[:2])["prediction"]
        np.testing.assert_allclose(out2, x[:2], rtol=1e-6)  # raw passthrough
    finally:
        server.stop()


def test_normalizer_applied_to_integer_typed_request(tmp_path):
    """Regression: the batcher must not cast the normalized (float) batch
    back to an integer request dtype — z-scores truncated to int are
    garbage. Programmatic submits can carry int arrays."""
    from deeplearning4j_tpu import NormalizerStandardize
    from deeplearning4j_tpu.datasets.dataset import DataSet
    raw = np.arange(60, dtype=np.float32).reshape(10, 6) * 7 + 3
    nz = NormalizerStandardize().fit(DataSet(raw, raw))
    net = _net(seed=0)
    p = str(tmp_path / "n.zip")
    ModelSerializer.write_model(net, p, normalizer=nz)
    registry = ModelRegistry()
    registry.load("v1", p)
    server = _component_server(None, registry=registry)
    try:
        registry.deploy("v1")
        x_int = np.asarray(raw[:2], np.int64)    # integer-typed request
        out = server.predict(x_int)["prediction"]
        expected = np.asarray(net.output(
            nz.transform_features(x_int.astype(np.float32))))
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
    finally:
        server.stop()


def test_fit_labels_normalizer_reverts_served_predictions(tmp_path):
    """Regression: a regression model trained against NORMALIZED labels
    (fit_labels=True) predicts in z-score label space; serving must revert
    its outputs to real units."""
    from deeplearning4j_tpu import NormalizerStandardize
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) * 100.0 + 500.0).astype(np.float32)
    nz = NormalizerStandardize(fit_labels=True).fit(DataSet(x, y))
    # a stub "perfect model" that predicts the NORMALIZED label exactly
    norm_y = nz.transform(DataSet(x, y)).labels

    class Oracle:
        def output(self, xx):
            # match rows of the padded batch back to known inputs; pad rows
            # (zeros) predict 0 in normalized space
            out = np.zeros((xx.shape[0], 1), np.float32)
            for i in range(xx.shape[0]):
                hit = np.where((np.abs(
                    nz.transform_features(x) - xx[i]).sum(axis=1)) < 1e-4)[0]
                if hit.size:
                    out[i] = norm_y[hit[0]]
            return out

    registry = ModelRegistry()
    registry.register("v1", Oracle(), transform=nz)
    server = _component_server(None, registry=registry)
    try:
        registry.deploy("v1")
        out = server.predict(x[:3])["prediction"]
        np.testing.assert_allclose(out, y[:3], rtol=1e-3, atol=1e-2)
    finally:
        server.stop()


def test_scan_dir_skips_unreadable_zip(tmp_path):
    """Regression: one truncated/foreign zip in scan_dir must not prevent
    the registry (and thus the server) from starting with healthy models."""
    ModelSerializer.write_model(_net(seed=0), str(tmp_path / "good.zip"))
    (tmp_path / "broken.zip").write_bytes(b"this is not a zip")
    registry = ModelRegistry(scan_dir=str(tmp_path))
    assert {v["version"] for v in registry.versions()} == {"good"}
    assert "broken.zip" in registry.scan_errors
    registry.deploy("good")
    assert registry.active_version == "good"


# ------------------------------------------- sequence-length bucketing

def _lstm_net(vocab=12, hidden=8, seed=0):
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).list()
            .layer(GravesLSTM(n_out=hidden, activation="tanh"))
            .layer(RnnOutputLayer(n_out=vocab, activation="softmax",
                                  loss="MCXENT"))
            .input_type(InputType.recurrent(vocab))
            .build())
    return MultiLayerNetwork(conf).init()


def _seq_x(vocab, *lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, t)][None] for t in lengths]


def test_seq_len_bucketing_coalesces_different_lengths():
    """Requests of DIFFERENT sequence lengths share one padded+masked batch
    and each caller's rows match the direct unpadded model.output — the
    prefill-leg satellite's core contract."""
    net = _lstm_net()
    server = _component_server(net, max_latency_ms=100.0)
    try:
        xs = _seq_x(12, 3, 5, 4)
        futs = [server.submit(x) for x in xs]     # one coalescing window
        results = [f.result(timeout=60) for f in futs]
        for x, res in zip(xs, results):
            pred = np.asarray(res["prediction"])
            assert pred.shape[1] == x.shape[1], "padding leaked to caller"
            np.testing.assert_allclose(pred, np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)
        # all three lengths (3, 5, 4) coalesced into ONE bucket-8 dispatch
        assert server.metrics.batches.get() == 1
        hist = {ls["len_bucket"]: v
                for ls, v in server.metrics.seq_bucket.series() if ls}
        assert hist == {"8": 1}
        # the observed key carries the (batch, length) bucket pair
        assert any(len(k) == 3 and k[2] == 8 for k in server.batcher.observed)
    finally:
        server.stop()


def test_seq_len_bucketing_zero_steady_state_recompiles_and_warm_swap():
    """Steady state over mixed lengths within one (batch, length) bucket
    pair never recompiles, and a hot-swap warm-up replays the seq keys (so
    the new version serves mixed lengths cold-free)."""
    net = _lstm_net(seed=1)
    server = _component_server(net, max_latency_ms=1.0)
    try:
        for t in (3, 5, 6, 2):
            server.predict(_seq_x(12, t, seed=t)[0])
        compiles = server.compile_tracker.total()
        for t in (4, 7, 5, 3):                 # same bucket-8 executable
            server.predict(_seq_x(12, t, seed=10 + t)[0])
        assert server.compile_tracker.total() == compiles, \
            "seq steady state recompiled"
        # swap to a new version: warm-up replays the seq (bucket, length)
        # keys with masks; serving after the swap stays recompile-free
        net2 = _lstm_net(seed=2)
        server.registry.register("v2", net2)
        server.deploy("v2")
        compiles = server.compile_tracker.total()
        x = _seq_x(12, 5, seed=99)[0]
        res = server.predict(x)
        assert res["version"] == "v2"
        np.testing.assert_allclose(np.asarray(res["prediction"]),
                                   np.asarray(net2.output(x)),
                                   rtol=1e-5, atol=1e-6)
        assert server.compile_tracker.total() == compiles, \
            "post-warm-up swap recompiled on a seq bucket"
    finally:
        server.stop()


def test_seq_len_bucketing_opt_out_keeps_legacy_signatures():
    net = _lstm_net(seed=3)
    server = _component_server(net, seq_len_bucketing=False)
    try:
        x = _seq_x(12, 5, seed=5)[0]
        res = server.predict(x)
        np.testing.assert_allclose(np.asarray(res["prediction"]),
                                   np.asarray(net.output(x)),
                                   rtol=1e-6, atol=1e-7)
        # legacy full-shape key: no length bucket dimension
        assert all(len(k) == 2 for k in server.batcher.observed)
    finally:
        server.stop()


def test_seq_requests_to_maskless_duck_typed_model_demote_to_legacy():
    """A custom model whose output() takes no mask must keep serving 3-D
    requests: the batcher demotes the seq batch to per-length legacy
    dispatches instead of TypeErroring the whole batch."""
    registry = ModelRegistry()
    registry.register("v1", StubModel(2.0))
    server = _component_server(None, registry=registry, max_latency_ms=100.0)
    try:
        registry.deploy("v1")
        xs = _seq_x(12, 3, 5)
        futs = [server.submit(x) for x in xs]
        for x, f in zip(xs, futs):
            res = f.result(timeout=60)
            np.testing.assert_allclose(np.asarray(res["prediction"]),
                                       x * 2.0, rtol=1e-6)
        # demoted dispatches record LEGACY (2-tuple) keys, no seq keys
        assert server.batcher.observed
        assert all(len(k) == 2 for k in server.batcher.observed)
    finally:
        server.stop()
