"""Resilience layer: retry/backoff/circuit-breaker policies, deadline
propagation, deterministic chaos injection, the health-aware FleetFrontend
with single-failover routing, and alert-gated canary deploys.

The acceptance tests at the bottom drive the ISSUE-8 react loop live with
ZERO real sleeps (ManualClock): one of two replicas dies mid-traffic -> the
frontend fails over (client error rate stays 0) -> the dead replica's
breaker shows `open` in /fleet/metrics -> recovery + a half-open probe
restore two-replica routing; a canary whose injected error ratio breaches
the SLO rule auto-rolls-back without any 5xx reaching clients and a healthy
canary auto-promotes (both visible in /alerts and /logs); a failed-over
request is ONE trace through the frontend's attempt spans and the winning
replica's server span, verified via /fleet/trace.
"""
import urllib.error

import numpy as np
import pytest

from deeplearning4j_tpu.resilience import (CircuitBreaker, CircuitOpenError,
                                           Deadline, DeadlineExceededError,
                                           FaultPlan, FaultRule, RetryBudget,
                                           RetryPolicy, current_deadline,
                                           deadline, guarded_call,
                                           is_retryable, is_server_fault)
from deeplearning4j_tpu.serving import FleetFrontend, ServingServer
from deeplearning4j_tpu.telemetry import FleetServer, MetricsRegistry, Tracer
from deeplearning4j_tpu.util.http import DEFAULT_TIMEOUT_S, get_json, post_json
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


class StubModel:
    def __init__(self, factor=2.0):
        self.factor = factor

    def output(self, x):
        return np.asarray(x) * self.factor


def _http_error(code):
    import email.message
    import io
    return urllib.error.HTTPError("http://x", code, "err",
                                  email.message.Message(), io.BytesIO(b"{}"))


# ------------------------------------------------------------ retry policy

def test_retry_exhaustion_raises_the_last_underlying_error(manual_clock):
    """Satellite: on attempt exhaustion the LAST real failure surfaces —
    never a synthetic 'retries exceeded' hiding it. Zero real sleeps."""
    errors = [ConnectionResetError("first"), TimeoutError("second"),
              ConnectionRefusedError("third and final")]
    calls = []

    def flaky():
        calls.append(1)
        raise errors[len(calls) - 1]

    policy = RetryPolicy(max_attempts=3, base_s=0.1,
                         sleep=manual_clock.advance)
    with pytest.raises(ConnectionRefusedError, match="third and final"):
        policy.call(flaky)
    assert len(calls) == 3 and policy.attempts_made == 3


def test_retry_budget_exhaustion_raises_last_error_not_a_wrapper(
        manual_clock):
    """Satellite: an empty budget denies the retry and the last underlying
    error raises immediately (no budget -> no amplification)."""
    budget = RetryBudget(capacity=1.0, refill_per_s=0.0)
    calls = []

    def always_down():
        calls.append(1)
        raise ConnectionResetError(f"attempt {len(calls)}")

    policy = RetryPolicy(max_attempts=5, base_s=0.01, budget=budget,
                         sleep=manual_clock.advance)
    with pytest.raises(ConnectionResetError, match="attempt 2"):
        policy.call(always_down)
    assert len(calls) == 2           # 1 retry allowed, the 2nd denied
    assert budget.denied == 1


def test_retry_budget_refills_on_the_injected_clock(manual_clock):
    budget = RetryBudget(capacity=2.0, refill_per_s=1.0)
    assert budget.try_spend() and budget.try_spend()
    assert not budget.try_spend()
    manual_clock.advance(1.5)
    assert budget.tokens() == pytest.approx(1.5)
    assert budget.try_spend()


def test_jittered_backoff_stays_within_base_and_cap():
    """Satellite: for every attempt the jittered delay lands in
    [base_s, min(cap_s, base_s * multiplier**attempt)]."""
    import random
    policy = RetryPolicy(max_attempts=3, base_s=0.1, cap_s=5.0,
                         multiplier=2.0, rng=random.Random(7))
    for attempt in range(16):
        ceiling = min(5.0, 0.1 * 2.0 ** attempt)
        for _ in range(50):
            b = policy.backoff_s(attempt)
            assert 0.1 <= b + 1e-12 and b <= ceiling + 1e-12
            assert b <= 5.0 + 1e-12


def test_retry_sleeps_are_the_jittered_backoffs(manual_clock):
    slept = []
    policy = RetryPolicy(max_attempts=4, base_s=0.5, cap_s=2.0,
                         sleep=slept.append)
    with pytest.raises(ConnectionResetError):
        policy.call(lambda: (_ for _ in ()).throw(ConnectionResetError()))
    assert len(slept) == 3
    assert all(0.5 <= s <= 2.0 for s in slept)


def test_retry_stops_when_the_total_deadline_is_spent(manual_clock):
    """total_timeout_s bounds the whole retry chain on the injected clock:
    once backoff sleeps consume it, the last error raises early."""
    calls = []

    def down():
        calls.append(1)
        raise ConnectionResetError("down")

    policy = RetryPolicy(max_attempts=50, base_s=1.0, cap_s=1.0,
                         total_timeout_s=2.5, sleep=manual_clock.advance)
    with pytest.raises(ConnectionResetError):
        policy.call(down)
    assert len(calls) < 50           # exhausted the budget, not the attempts
    assert 2 <= len(calls) <= 4


def test_retry_does_not_retry_non_retryable_errors(manual_clock):
    calls = []

    def bad_request():
        calls.append(1)
        raise _http_error(404)

    policy = RetryPolicy(max_attempts=5, sleep=manual_clock.advance)
    with pytest.raises(urllib.error.HTTPError):
        policy.call(bad_request)
    assert len(calls) == 1


def test_retries_count_into_retries_total_by_reason(manual_clock):
    reg = MetricsRegistry()
    policy = RetryPolicy(max_attempts=3, base_s=0.01, registry=reg,
                         sleep=manual_clock.advance)
    with pytest.raises(ConnectionResetError):
        policy.call(lambda: (_ for _ in ()).throw(ConnectionResetError()))
    c = reg.get("retries_total")
    assert c.get(reason="ConnectionResetError") == 2


def test_retryability_classification():
    assert is_retryable(_http_error(500)) and is_retryable(_http_error(429))
    assert not is_retryable(_http_error(404))
    assert is_retryable(ConnectionResetError()) and is_retryable(OSError())
    assert not is_retryable(DeadlineExceededError())
    assert not is_retryable(CircuitOpenError())
    assert not is_retryable(ValueError())
    # 429 is the server protecting itself, not the server being broken
    assert is_server_fault(_http_error(500))
    assert not is_server_fault(_http_error(429))
    assert not is_server_fault(CircuitOpenError())
    # protocol corruption mid-response (BadStatusLine/IncompleteRead are
    # HTTPException, NOT OSError): the peer is as dead as a reset one —
    # retryable AND a server fault (the breaker must open, not record
    # success as if the target had answered)
    import http.client
    assert is_retryable(http.client.BadStatusLine("garbage"))
    assert is_server_fault(http.client.IncompleteRead(b"partial"))


def test_record_outcome_counts_protocol_corruption_as_failure(manual_clock):
    """A replica emitting garbage status lines must open its breaker like
    one refusing connections — not accrue successes."""
    import http.client
    from deeplearning4j_tpu.resilience.policy import record_outcome
    br = CircuitBreaker(min_calls=2, failure_ratio=0.5, window=10)
    record_outcome(br, http.client.BadStatusLine("x"))
    record_outcome(br, http.client.RemoteDisconnected("y"))
    assert br.state == "open"


# --------------------------------------------------------------- deadlines

def test_deadline_clamps_and_expires_on_the_injected_clock(manual_clock):
    with deadline(2.0) as dl:
        assert current_deadline() is dl
        assert dl.clamp(5.0) == pytest.approx(2.0)
        assert dl.clamp(0.5) == pytest.approx(0.5)
        manual_clock.advance(1.5)
        assert dl.remaining() == pytest.approx(0.5)
        manual_clock.advance(1.0)
        assert dl.expired
        with pytest.raises(DeadlineExceededError):
            dl.clamp(1.0)
    assert current_deadline() is None


def test_deadlines_nest_and_unbounded_never_expires(manual_clock):
    unbounded = Deadline(None)
    assert unbounded.remaining() is None and not unbounded.expired
    assert unbounded.clamp(3.0) == 3.0 and unbounded.clamp(None) is None
    with deadline(10.0):
        with deadline(1.0) as inner:
            assert current_deadline() is inner      # innermost wins
        outer = current_deadline()
        assert outer is not None and outer.timeout_s == 10.0


def test_inner_deadline_cannot_outlive_the_enclosing_one(manual_clock):
    """Nested budgets only SHRINK: entering a LONGER inner deadline (e.g.
    RetryPolicy(total_timeout_s=60) inside `with deadline(0.5)`) must keep
    the outer expiry, or the inner scope would un-clamp socket timeouts
    past the caller's total budget."""
    with deadline(0.5):
        with deadline(60.0) as inner:
            assert inner.remaining() == pytest.approx(0.5)
        with Deadline(None) as unbounded:       # unbounded inherits too
            assert unbounded.remaining() == pytest.approx(0.5)
        manual_clock.advance(0.6)
        with deadline(60.0) as spent:
            assert spent.expired
            with pytest.raises(DeadlineExceededError):
                spent.clamp(1.0)
    # a fresh top-level deadline is unaffected
    with deadline(60.0) as top:
        assert top.remaining() == pytest.approx(60.0)


def test_util_http_clamps_to_the_active_deadline(manual_clock, monkeypatch):
    """Satellite: every outbound call gets an explicit socket timeout —
    DEFAULT_TIMEOUT_S when none is given — clamped to the thread's Deadline;
    a spent budget fails fast WITHOUT opening a socket."""
    import deeplearning4j_tpu.util.http as http_mod
    seen = []

    class FakeResp:
        status = 200

        def read(self):
            return b'{"ok": true}'

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(req, timeout=None):
        seen.append(timeout)
        return FakeResp()

    monkeypatch.setattr(http_mod.urllib.request, "urlopen", fake_urlopen)
    post_json("http://peer/x", {})
    assert seen[-1] == DEFAULT_TIMEOUT_S          # never an infinite wait
    get_json("http://peer/x", timeout=120.0)
    assert seen[-1] == 120.0
    with deadline(2.0):
        post_json("http://peer/x", {}, timeout=60.0)
        assert seen[-1] == pytest.approx(2.0)     # clamped to the budget
        manual_clock.advance(3.0)
        n = len(seen)
        with pytest.raises(DeadlineExceededError):
            post_json("http://peer/x", {})
        assert len(seen) == n                     # no socket was opened


# ---------------------------------------------------------- circuit breaker

def _trip(breaker, n=5):
    for _ in range(n):
        breaker.record_failure()


def test_breaker_half_open_recloses_after_one_success(manual_clock):
    """Satellite: closed -> open on the failure ratio, half-open after the
    cool-off, ONE successful probe re-closes with a clean window."""
    br = CircuitBreaker(failure_ratio=0.5, window=10, min_calls=3,
                        open_for_s=30.0, name="r1")
    assert br.state == "closed" and br.allow()
    _trip(br, 3)
    assert br.state == "open" and br.opens == 1
    assert not br.allow()                     # fail fast while open
    manual_clock.advance(29.0)
    assert not br.allow()                     # cool-off not yet elapsed
    manual_clock.advance(1.5)
    assert br.state == "half_open"
    assert br.allow()                         # claims the single probe slot
    assert not br.allow()                     # half_open_max=1: slot busy
    br.record_success()
    assert br.state == "closed"
    assert br.to_dict()["window_calls"] == 0  # clean slate


def test_breaker_half_open_reopens_after_one_failure(manual_clock):
    br = CircuitBreaker(failure_ratio=0.5, window=10, min_calls=3,
                        open_for_s=30.0)
    _trip(br, 3)
    manual_clock.advance(30.5)
    assert br.allow()                         # the half-open probe
    br.record_failure()
    assert br.state == "open" and br.opens == 2
    assert not br.allow()
    # and the NEXT cool-off gives another probe
    manual_clock.advance(30.5)
    assert br.allow()
    br.record_success()
    assert br.state == "closed"


def test_breaker_release_probe_frees_the_half_open_slot(manual_clock):
    """A probe that ends with no proof either way (the CALLER'S deadline
    expired mid-flight) must free its slot without transitioning —
    otherwise the breaker wedges half-open, rejecting forever."""
    br = CircuitBreaker(min_calls=2, open_for_s=10.0)
    _trip(br, 2)
    manual_clock.advance(10.5)
    assert br.allow()
    assert not br.allow()            # the single slot is claimed
    br.release_probe()               # no-proof outcome
    assert br.state == "half_open"   # no transition happened
    assert br.allow()                # slot is probeable again
    br.record_success()
    assert br.state == "closed"
    br.release_probe()               # closed: a no-op, never underflows
    assert br.state == "closed"


def test_breaker_min_calls_and_ratio_gate(manual_clock):
    br = CircuitBreaker(failure_ratio=0.5, window=20, min_calls=5)
    br.record_failure()                       # one early failure: no trip
    assert br.state == "closed"
    for _ in range(6):
        br.record_success()
    for _ in range(4):
        br.record_failure()
    assert br.state == "closed"               # 5/11 < 0.5
    br.record_failure()
    assert br.state == "open"                 # 6/12 >= 0.5


def test_breaker_transitions_are_observable(manual_clock):
    seen = []
    br = CircuitBreaker(min_calls=2, open_for_s=5.0,
                        on_transition=lambda b, old, new: seen.append(
                            (old, new)))
    _trip(br, 2)
    manual_clock.advance(5.5)
    br.state                                  # tick -> half-open
    br.record_success()
    assert seen == [("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")]


def test_guarded_call_composes_breaker_inside_retry(manual_clock):
    """The breaker is consulted per ATTEMPT: once it opens mid-retry the
    remaining attempts fail fast, and CircuitOpenError itself never
    retries."""
    br = CircuitBreaker(failure_ratio=0.5, window=4, min_calls=2,
                        open_for_s=60.0, name="svc")
    calls = []

    def down():
        calls.append(1)
        raise ConnectionResetError("down")

    retry = RetryPolicy(max_attempts=6, base_s=0.01,
                        sleep=manual_clock.advance)
    with pytest.raises(CircuitOpenError):
        guarded_call(down, retry=retry, breaker=br)
    assert len(calls) == 2                    # third attempt hit the breaker
    assert br.state == "open"
    n = len(calls)
    with pytest.raises(CircuitOpenError):
        guarded_call(down, breaker=br)        # fail fast, no call made
    assert len(calls) == n
    # a 4xx answer counts as the target being ALIVE (success for the breaker)
    manual_clock.advance(61.0)
    with pytest.raises(urllib.error.HTTPError):
        guarded_call(lambda: (_ for _ in ()).throw(_http_error(404)),
                     breaker=br)
    assert br.state == "closed"               # half-open probe re-closed


# ------------------------------------------------------------------- chaos

def test_fault_plan_json_round_trip():
    plan = FaultPlan([
        FaultRule("reset", match="replica-b", name="kill-b"),
        FaultRule("error", match="/predict", method="post", status=503,
                  body={"error": "boom"}, after=2, count=5,
                  probability=0.5),
        FaultRule("latency", match="", latency_s=0.25, active=False),
        FaultRule("wedge", match="/healthz"),
        FaultRule("unhealthy", match="b:80"),
    ], seed=7)
    doc = plan.to_json()
    again = FaultPlan.from_json(doc, seed=7)
    assert again.to_json() == doc
    assert [r.kind for r in again.rules] == ["reset", "error", "latency",
                                             "wedge", "unhealthy"]
    assert again.rules[1].method == "POST" and again.rules[1].after == 2
    with pytest.raises(ValueError):
        FaultRule("explode", match="x")


def test_disk_fault_rules_json_round_trip():
    """The four disk kinds JSON-round-trip like the HTTP rules, are
    invisible to the HTTP matcher, and path-match through matches_path."""
    plan = FaultPlan([
        FaultRule("torn_write", match="model.zip", after=2, count=1,
                  name="tear"),
        FaultRule("bitflip", match="coefficients", probability=0.5),
        FaultRule("enospc", match="ckpt-", name="disk-full", active=False),
        FaultRule("slow_disk", match="", latency_s=0.25),
    ], seed=7)
    doc = plan.to_json()
    again = FaultPlan.from_json(doc, seed=7)
    assert again.to_json() == doc
    assert [r.kind for r in again.rules] == ["torn_write", "bitflip",
                                             "enospc", "slow_disk"]
    assert again.rules[3].latency_s == 0.25
    for r in again.rules:
        assert not r.matches("POST", "http://h/model.zip")  # never HTTP
    assert again.rules[0].matches_path("/ck/tmp-1/model.zip")
    assert not again.rules[0].matches_path("/ck/tmp-1/state.json")
    assert not again.rules[2].matches_path("/ck/ckpt-1/x")  # inactive


def test_disk_faults_fire_through_the_fs_seam(tmp_path, manual_clock):
    """FaultPlan.install() hooks util.fs: torn_write halves the on-disk
    bytes, bitflip flips one bit (size preserved), enospc raises
    OSError(ENOSPC), slow_disk advances the injected clock — all
    deterministic, counted in plan.injected()."""
    import errno
    from deeplearning4j_tpu.util import fs

    data = bytes(range(256)) * 4
    plan = FaultPlan([
        FaultRule("slow_disk", match="slow", latency_s=1.5),
        FaultRule("torn_write", match="torn.bin", name="tear"),
        FaultRule("bitflip", match="flip.bin", name="flip"),
        FaultRule("enospc", match="full.bin", name="full"),
    ], seed=3)
    with plan:
        t0 = manual_clock.monotonic()
        fs.write_bytes(tmp_path / "slow-a.bin", data)
        assert manual_clock.monotonic() - t0 == pytest.approx(1.5)
        fs.write_bytes(tmp_path / "torn.bin", data)
        fs.write_bytes(tmp_path / "flip.bin", data)
        with pytest.raises(OSError) as ei:
            fs.write_bytes(tmp_path / "full.bin", data)
        assert ei.value.errno == errno.ENOSPC
    assert (tmp_path / "torn.bin").stat().st_size == len(data) // 2
    flipped = (tmp_path / "flip.bin").read_bytes()
    assert len(flipped) == len(data)
    diff = [i for i in range(len(data)) if flipped[i] != data[i]]
    assert diff == [len(data) // 2]
    assert not (tmp_path / "full.bin").exists()
    assert plan.injected() == {"slow_disk": 1, "tear": 1, "flip": 1,
                               "full": 1}
    # uninstalled: writes pass through clean
    fs.write_bytes(tmp_path / "torn.bin", data)
    assert (tmp_path / "torn.bin").stat().st_size == len(data)


def test_fault_rule_after_count_probability_and_method(manual_clock):
    plan = FaultPlan([FaultRule("error", match="/p", after=1, count=2)],
                     seed=0)
    out = [plan.intercept("POST", "http://h/p", 5.0) for _ in range(5)]
    assert [o is None for o in out] == [True, False, False, True, True]
    assert plan.injected() == {"error": 2}
    # method filter
    plan2 = FaultPlan([FaultRule("error", match="/p", method="POST")])
    assert plan2.intercept("GET", "http://h/p", 5.0) is None
    assert plan2.intercept("POST", "http://h/p", 5.0) is not None
    # seeded probability draws are reproducible
    runs = []
    for _ in range(2):
        p = FaultPlan([FaultRule("error", match="", probability=0.5)],
                      seed=42)
        runs.append([p.intercept("GET", "u", 1.0) is not None
                     for _ in range(20)])
    assert runs[0] == runs[1] and 3 < sum(runs[0]) < 17


def test_wedge_and_latency_advance_the_injected_clock(manual_clock):
    """A wedged socket costs the caller its full timeout — paid on the
    ManualClock, zero real sleeps; latency rules compose (non-terminal)."""
    plan = FaultPlan([FaultRule("latency", match="/p", latency_s=2.0),
                      FaultRule("error", match="/p", status=500)])
    t0 = manual_clock.monotonic()
    out = plan.intercept("POST", "http://h/p", 5.0)
    assert out is not None and out[0] == 500
    assert manual_clock.monotonic() - t0 == pytest.approx(2.0)
    wedge = FaultPlan([FaultRule("wedge", match="/w")])
    t1 = manual_clock.monotonic()
    with pytest.raises(TimeoutError, match="wedged"):
        wedge.intercept("GET", "http://h/w", 7.0)
    assert manual_clock.monotonic() - t1 == pytest.approx(7.0)


def test_set_active_scripts_kill_and_recover():
    plan = FaultPlan([FaultRule("reset", match="b", name="kill-b")])
    assert plan.intercept("GET", "http://a/x", 1.0) is None  # no match
    with pytest.raises(ConnectionResetError):
        plan.intercept("GET", "http://b/x", 1.0)
    assert plan.set_active("kill-b", False) == 1
    assert plan.intercept("GET", "http://b/x", 1.0) is None
    with pytest.raises(KeyError):
        plan.set_active("nope", False)


def test_fault_plan_installs_into_util_http_without_sockets():
    """The chaos seam lives in util.http: canned responses and transport
    errors come back through post_json/get_json exactly like real ones,
    and uninstall restores pass-through."""
    plan = FaultPlan([
        FaultRule("error", match="fake-host/a", status=500, name="e"),
        FaultRule("reset", match="fake-host/r", name="r"),
        FaultRule("unhealthy", match="fake-host/healthz", name="u"),
        FaultRule("error", match="fake-host/ok", status=200,
                  body={"fine": 1}, name="ok")])
    with plan:
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_json("http://fake-host/a", {}, timeout=1.0)
        assert ei.value.code == 500
        with pytest.raises(ConnectionResetError):
            post_json("http://fake-host/r", {}, timeout=1.0)
        code, body = get_json("http://fake-host/healthz", timeout=1.0,
                              with_status=True)
        assert code == 503 and body["health"] == "unhealthy"
        assert post_json("http://fake-host/ok", {}, timeout=1.0) == \
            {"fine": 1}
        # the injected HTTPError is retryable/breaker-countable like a
        # real one
        assert is_retryable(ei.value) and is_server_fault(ei.value)
    from deeplearning4j_tpu.util import http as http_mod
    assert http_mod._fault_injector is None


# --------------------------------------------------- scan_errors satellite

def test_registry_scan_errors_surface_as_degraded_health():
    """Satellite: a zip the startup scan could not load was recorded but
    invisible to /healthz (and so to the fleet view) — now it degrades the
    registry component while the server keeps serving."""
    s = ServingServer(StubModel(), port=0, alert_interval_s=0).start()
    try:
        code, h = get_json(s.url + "/healthz", timeout=30, with_status=True)
        assert code == 200 and h["components"]["registry"]["status"] == \
            "healthy"
        s.registry.scan_errors["broken.zip"] = "BadZipFile: corrupt"
        code, h = get_json(s.url + "/healthz", timeout=30, with_status=True)
        assert code == 200                      # degraded serves, 503 never
        assert h["health"] == "degraded"
        comp = h["components"]["registry"]
        assert comp["status"] == "degraded"
        assert comp["scan_errors"] == {"broken.zip": "BadZipFile: corrupt"}
    finally:
        s.stop()


# ------------------------------------------------------- frontend plumbing

def test_frontend_rejects_misconfiguration():
    with pytest.raises(ValueError):
        FleetFrontend([])
    with pytest.raises(ValueError):
        FleetFrontend(["http://a:1", "http://b:1"], names=["one"])
    with pytest.raises(ValueError):
        FleetFrontend(["http://a:1", "http://b:1"], names=["x", "x"])


def test_rollback_during_canary_transition_is_409_not_fleet_wide():
    """A /rollback racing a canary's DEPLOYING/PROMOTING/ROLLING_BACK
    broadcast must be rejected (409) — not reinterpreted as 'revert the
    ENTIRE stable fleet to its previous version'."""
    from deeplearning4j_tpu.serving import canary as canary_states
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, alert_interval_s=0).start()
    try:
        for srv in (s1, s2):
            srv.registry.register("v2", StubModel(3.0))
            post_json(srv.url + "/deploy", {"version": "v2"}, timeout=30)
        fe.canary.state = canary_states.DEPLOYING     # in-flight deploy POST
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_json(fe.url + "/rollback", {}, timeout=30)
        assert ei.value.code == 409
        # nobody was reverted
        assert s1.registry.active_version == "v2"
        assert s2.registry.active_version == "v2"
        fe.canary.state = canary_states.IDLE
        post_json(fe.url + "/rollback", {}, timeout=30)   # idle: fleet-wide
        assert s1.registry.active_version == "v1"
        assert s2.registry.active_version == "v1"
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_registry_subscriber_applies_broker_fanned_events():
    """Cross-host registry view: a deploy routed through the frontend fans
    out over the streaming broker and a RegistrySubscriber applies it on a
    host the frontend does not even route to."""
    import time
    from deeplearning4j_tpu.serving import RegistrySubscriber
    from deeplearning4j_tpu.streaming import BrokerClient, MessageBroker
    broker = MessageBroker(port=0, registry=MetricsRegistry()).start()
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    other = ServingServer(StubModel(), version="v1", port=0,
                          alert_interval_s=0)     # never started: local only
    other.registry.register("v2", StubModel(3.0))
    pub = BrokerClient(port=broker.port)
    sub_client = BrokerClient(port=broker.port)
    sub = RegistrySubscriber(other, sub_client, poll_timeout_s=0.05).start()
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"], broker=pub,
                       health_interval_s=1e9, alert_interval_s=0).start()
    try:
        for srv in (s1, s2):
            srv.registry.register("v2", StubModel(3.0))
        res = post_json(fe.url + "/deploy", {"version": "v2"}, timeout=30)
        assert res["version"] == "v2"
        assert s1.registry.active_version == "v2"
        assert s2.registry.active_version == "v2"
        t0 = time.monotonic()
        while other.registry.active_version != "v2":
            assert time.monotonic() - t0 < 15.0, sub.errors
            time.sleep(0.02)
        assert sub.applied == 1 and sub.errors == []
    finally:
        fe.stop()
        sub.close()
        pub.close()
        s1.stop()
        s2.stop()
        broker.stop()


# -------------------------------------------------------------- acceptance

def test_acceptance_replica_death_failover_breaker_recovery(manual_clock):
    """ISSUE 8 acceptance: with one of two replicas fault-injected dead,
    /predict error rate at the front-end stays 0 (failover), the dead
    replica's breaker shows `open` in /fleet/metrics, and after recovery
    the half-open probe restores two-replica routing — zero real sleeps."""
    s1 = ServingServer(StubModel(), port=0, alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), port=0, alert_interval_s=0).start()
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, breaker_min_calls=2,
                       breaker_window=10, breaker_open_for_s=30.0,
                       alert_interval_s=0).start()
    fleet = FleetServer([fe.url], names=["frontend"], interval_s=0.0).start()
    total = 0

    def predict():
        nonlocal total
        total += 1
        r = post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                      timeout=30)
        assert r["prediction"] == [[2.0, 4.0]], r
        return r

    try:
        served = {predict()["replica"] for _ in range(4)}
        assert served == {"a", "b"}              # both replicas in rotation

        plan = FaultPlan([FaultRule("reset", match=s2.url + "/predict",
                                    name="kill-b")])
        with plan:
            kill_phase = [predict() for _ in range(8)]
            # failover kept every client answer a 200
            assert all(r["prediction"] == [[2.0, 4.0]] for r in kill_phase)
            assert all(r["replica"] == "a" for r in kill_phase[-4:])
            assert any(r["attempts"] == 2 for r in kill_phase)  # failovers

            snap = get_json(fe.url + "/metrics", timeout=30)
            assert snap["replicas"]["b"]["breaker"]["state"] == "open"
            assert snap["frontend_failovers_total"] >= 1
            # the ejection is DATA on the fleet plane, not absence
            fm = get_json(fleet.url + "/fleet/metrics", timeout=30)
            inst = fm["instances"]["frontend"]
            assert inst["breaker_state"]["replica=b"] == 2.0
            assert inst["breaker_state"]["replica=a"] == 0.0
            assert inst["replicas"]["b"]["breaker"]["state"] == "open"
            fh = get_json(fleet.url + "/fleet/healthz", timeout=30)
            assert fh["status"] == "degraded"    # visible, still serving
            # the frontend itself: degraded replica probe, 200 /healthz
            # (its OWN load balancer must not pull a serving front door)
            code, h = get_json(fe.url + "/healthz", timeout=30,
                               with_status=True)
            assert code == 200 and h["health"] == "degraded"
            assert h["components"]["replica:b"]["status"] == "degraded"
            assert h["components"]["pool"]["status"] == "degraded"

            # ---- recovery: kill switch off, cool-off elapses -------------
            plan.set_active("kill-b", False)
            r = predict()
            assert r["replica"] == "a"           # breaker still open: no b
            manual_clock.advance(31.0)           # cool-off on the clock
            recovered = {predict()["replica"] for _ in range(6)}
            assert recovered == {"a", "b"}       # half-open probe re-admitted
            snap = get_json(fe.url + "/metrics", timeout=30)
            assert snap["replicas"]["b"]["breaker"]["state"] == "closed"

        # error rate at the front-end stayed 0 THROUGHOUT
        snap = get_json(fe.url + "/metrics", timeout=30)
        assert snap["frontend_requests_total"] == {"code=200": float(total)}
        # breaker transitions were logged + counted
        assert snap["breaker_transitions_total"]["replica=b,state=open"] \
            == 1.0
        logs = get_json(fe.url + "/logs", timeout=30)
        msgs = [r["message"] for r in logs["records"]]
        assert "breaker_transition" in msgs
    finally:
        fleet.stop()
        fe.stop()
        s1.stop()
        s2.stop()


def test_acceptance_bad_canary_rolls_back_without_client_5xx(manual_clock):
    """ISSUE 8 acceptance: a canary version whose injected error ratio
    breaches the SLO rule is auto-rolled-back, no 5xx ever reaches a
    front-end client (failover serves the stable version throughout), and
    the transition is visible in /alerts and trace-correlated /logs."""
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2.registry.register("v2", StubModel(3.0))
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, breaker_min_calls=3,
                       breaker_open_for_s=30.0, alert_interval_s=0,
                       canary_opts={"bake_s": 120.0, "min_requests": 4,
                                    "error_ratio": 0.25,
                                    "window_s": 300.0}).start()
    try:
        res = post_json(fe.url + "/deploy",
                        {"version": "v2", "canary": 0.5}, timeout=30)
        assert res["canary"]["state"] == "observing"
        assert res["canary"]["replica"] == "b"
        assert s2.registry.active_version == "v2"
        assert s1.registry.active_version == "v1"    # stable fleet untouched
        fe.alerts.evaluate()                         # baseline window sample

        plan = FaultPlan([FaultRule("error", match=s2.url + "/predict",
                                    status=500, name="bad-canary")])
        rollback_events = []
        with plan:
            for _ in range(8):
                r = post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                              timeout=30)
                # every answer is the STABLE version's output: the canary
                # attempt failed over to a stable replica
                assert r["prediction"] == [[2.0, 4.0]], r
            manual_clock.advance(5.0)
            rollback_events = fe.alerts.evaluate()   # the gate fires -> react

        fired = [e for e in rollback_events
                 if e["rule"] == "canary_error_ratio"]
        assert fired and fired[0]["state"] == "firing"
        assert fired[0]["value"] > 0.25
        assert fe.canary.state == "idle"
        last = fe.canary.history[-1]
        assert last["outcome"] == "rolled_back"
        assert last["reason"] == "canary_error_ratio"
        assert s2.registry.active_version == "v1"    # replica redeployed old
        # zero 5xx reached clients
        snap = get_json(fe.url + "/metrics", timeout=30)
        assert set(snap["frontend_requests_total"]) == {"code=200"}
        assert snap["canary_rollbacks_total"] == 1.0
        # visible in /alerts ...
        al = get_json(fe.url + "/alerts", timeout=30)
        assert al["canary"]["rollbacks"] == 1
        assert al["canary"]["history"][-1]["outcome"] == "rolled_back"
        # ... and in /logs: the rollback event, plus trace-correlated
        # failed-attempt records (each carries the request's trace id)
        logs = get_json(fe.url + "/logs?level=error", timeout=30)
        assert any(r["message"] == "canary_rolled_back"
                   for r in logs["records"])
        warns = get_json(fe.url + "/logs?level=warning", timeout=30)
        failed = [r for r in warns["records"]
                  if r["message"] == "predict_attempt_failed"]
        assert failed and all(r.get("trace_id") for r in failed)
        tr = get_json(fe.url + "/trace", timeout=30)
        span_traces = {e["args"].get("trace_id")
                       for e in tr["traceEvents"] if e.get("ph") == "X"}
        assert failed[-1]["trace_id"] in span_traces
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_acceptance_healthy_canary_auto_promotes(manual_clock):
    """The other gate outcome: a canary that bakes healthy for bake_s with
    enough traffic auto-promotes to the whole fleet."""
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    for srv in (s1, s2):
        srv.registry.register("v2", StubModel(3.0))
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, alert_interval_s=0,
                       canary_opts={"bake_s": 60.0, "min_requests": 3,
                                    "error_ratio": 0.5,
                                    "window_s": 300.0}).start()
    promote_events = []
    fe.alerts.add_sink(promote_events.append)
    try:
        post_json(fe.url + "/deploy", {"version": "v2", "canary": 0.5},
                  timeout=30)
        fe.alerts.evaluate()
        outputs = set()
        for _ in range(8):
            r = post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                          timeout=30)
            outputs.add(r["prediction"][0][0])
        assert outputs == {2.0, 3.0}          # both cohorts actually served
        manual_clock.advance(30.0)
        fe.alerts.evaluate()
        assert fe.canary.state == "observing"  # still baking: no promote
        manual_clock.advance(31.0)
        fe.alerts.evaluate()
        assert fe.canary.state == "idle"
        assert fe.canary.history[-1]["outcome"] == "promoted"
        assert s1.registry.active_version == "v2"   # fleet-wide now
        assert s2.registry.active_version == "v2"
        assert any(e["rule"] == "canary_promote_ready"
                   and e["state"] == "firing" for e in promote_events)
        al = get_json(fe.url + "/alerts", timeout=30)
        assert al["canary"]["promotions"] == 1
        logs = get_json(fe.url + "/logs", timeout=30)
        assert any(r["message"] == "canary_promoted"
                   for r in logs["records"])
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_failed_rollback_keeps_bad_version_out_of_stable_rotation(
        manual_clock):
    """If the rollback POST cannot land (canary replica unreachable right
    when its bad version must come off), the replica must NOT silently
    rejoin the stable pool still serving the bad version: it stays in the
    (idle, zero-fraction) canary cohort — failover target only — a new
    canary over the wreckage is refused, and a fleet-wide /deploy
    re-admits it."""
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    for srv in (s1, s2):
        srv.registry.register("v2", StubModel(3.0))
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, breaker_min_calls=100,
                       alert_interval_s=0,
                       canary_opts={"bake_s": 120.0, "min_requests": 4,
                                    "error_ratio": 0.25,
                                    "window_s": 300.0}).start()
    try:
        post_json(fe.url + "/deploy", {"version": "v2", "canary": 0.5},
                  timeout=30)
        fe.alerts.evaluate()
        # the canary predicts fail AND its /rollback endpoint is dead too
        plan = FaultPlan([
            FaultRule("error", match=s2.url + "/predict", status=500,
                      name="bad-canary"),
            FaultRule("reset", match=s2.url + "/rollback", name="dead-b")])
        with plan:
            for _ in range(8):
                post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                          timeout=30)
            manual_clock.advance(5.0)
            fe.alerts.evaluate()             # breach fires -> rollback fails
        last = fe.canary.history[-1]
        assert last["outcome"] == "rolled_back"
        assert last["undeployed"] is False
        assert s2.registry.active_version == "v2"    # bad version still up
        assert fe.canary.state == "idle"
        # ... but b is NOT back in the stable rotation: primary traffic
        # goes to a only (b remains a failover target)
        assert fe._replica("b").cohort == "canary"
        assert {post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                          timeout=30)["replica"] for _ in range(6)} == {"a"}
        # the failure is loud: logged + broker-visible history entry
        logs = get_json(fe.url + "/logs?level=error", timeout=30)
        assert any(r["message"] == "canary_rollback_failed"
                   for r in logs["records"])
        # a new canary over the wreckage is refused
        with pytest.raises(urllib.error.HTTPError):
            post_json(fe.url + "/deploy", {"version": "v2", "canary": 0.5},
                      timeout=30)
        # fleet-wide deploy re-admits b with the fleet version
        post_json(fe.url + "/deploy", {"version": "v1"}, timeout=30)
        assert s2.registry.active_version == "v1"
        assert fe._replica("b").cohort == "stable"
        served = {post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                            timeout=30)["replica"] for _ in range(6)}
        assert served == {"a", "b"}
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_back_to_back_canaries_do_not_inherit_prior_errors(manual_clock):
    """A healthy canary started right after a rolled-back one (inside the
    SLO rule's window_s) must promote, not roll back: the engine's windowed
    counter history for the reused cohort label-set is dropped at canary
    start, so the new rule windows only THIS deploy's traffic."""
    s1 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), version="v1", port=0,
                       alert_interval_s=0).start()
    for srv in (s1, s2):
        srv.registry.register("v2", StubModel(3.0))
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, breaker_min_calls=100,
                       alert_interval_s=0,
                       canary_opts={"bake_s": 60.0, "min_requests": 3,
                                    "error_ratio": 0.25,
                                    "window_s": 300.0}).start()
    try:
        # ---- canary 1: injected errors -> rolled back --------------------
        post_json(fe.url + "/deploy", {"version": "v2", "canary": 0.5},
                  timeout=30)
        fe.alerts.evaluate()
        plan = FaultPlan([FaultRule("error", match=s2.url + "/predict",
                                    status=500, name="bad")])
        with plan:
            for _ in range(8):
                post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                          timeout=30)
            manual_clock.advance(5.0)
            fe.alerts.evaluate()
        assert fe.canary.history[-1]["outcome"] == "rolled_back"
        assert s2.registry.active_version == "v1"

        # ---- canary 2, healthy, started well inside window_s -------------
        manual_clock.advance(10.0)
        post_json(fe.url + "/deploy", {"version": "v2", "canary": 0.5},
                  timeout=30)
        fe.alerts.evaluate()          # must NOT see canary 1's error deltas
        assert fe.canary.state == "observing", fe.canary.history[-1]
        for _ in range(8):
            post_json(fe.url + "/predict", {"data": [[1.0, 2.0]]},
                      timeout=30)
        manual_clock.advance(61.0)    # bake elapses; still within window_s
        fe.alerts.evaluate()
        assert fe.canary.history[-1]["outcome"] == "promoted", \
            fe.canary.history[-1]
        assert s1.registry.active_version == "v2"
        assert s2.registry.active_version == "v2"
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_acceptance_failed_over_request_is_one_trace():
    """ISSUE 8 acceptance: a retried/failed-over request appears as ONE
    trace — front-end server span -> per-attempt child spans with retry
    attributes -> the winning replica's server span — via /fleet/trace."""
    s1 = ServingServer(StubModel(), port=0, alert_interval_s=0).start()
    s2 = ServingServer(StubModel(), port=0, alert_interval_s=0).start()
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, breaker_min_calls=100,
                       alert_interval_s=0).start()
    fleet = FleetServer([fe.url, s1.url], names=["frontend", "a"],
                        interval_s=0.0).start()
    client = Tracer(enabled=True)
    try:
        plan = FaultPlan([FaultRule("reset", match=s2.url + "/predict",
                                    name="kill-b")])
        failover_trace = None
        with plan:
            for _ in range(6):
                with client.span("client_call") as cs:
                    r = post_json(fe.url + "/predict",
                                  {"data": [[1.0, 2.0]]}, timeout=30)
                if r["attempts"] == 2 and r["replica"] == "a":
                    failover_trace = cs.trace_id
                    break
        assert failover_trace, "no request failed over b -> a"

        # frontend side: server span -> frontend_predict -> two attempts
        tr = get_json(fe.url + "/trace", timeout=30)
        spans = [e for e in tr["traceEvents"] if e.get("ph") == "X"
                 and e["args"].get("trace_id") == failover_trace]
        by_name = {}
        for e in spans:
            by_name.setdefault(e["name"], []).append(e)
        server = by_name["http /predict"][0]
        root = by_name["frontend_predict"][0]
        attempts = sorted(by_name["attempt"],
                          key=lambda e: e["args"]["attempt"])
        assert root["args"]["parent_id"] == server["args"]["span_id"]
        assert len(attempts) == 2
        assert [a["args"]["retry"] for a in attempts] == [False, True]
        assert [a["args"]["replica"] for a in attempts] == ["b", "a"]
        assert attempts[0]["args"]["error"] == "ConnectionResetError"
        for a in attempts:
            assert a["args"]["parent_id"] == root["args"]["span_id"]

        # winning replica side: ITS server span continues the same trace,
        # parented on the winning attempt
        atr = get_json(s1.url + "/trace", timeout=30)
        aspans = [e for e in atr["traceEvents"] if e.get("ph") == "X"
                  and e["args"].get("trace_id") == failover_trace]
        anames = {e["name"] for e in aspans}
        assert {"http /predict", "predict"} <= anames, anames
        aserver = next(e for e in aspans if e["name"] == "http /predict")
        assert aserver["args"]["parent_id"] == \
            attempts[1]["args"]["span_id"]

        # and the fleet plane shows the whole thing across both hosts
        ftr = get_json(fleet.url + "/fleet/trace", timeout=30)
        lanes_with_trace = {e["pid"] for e in ftr["traceEvents"]
                            if e.get("ph") == "X"
                            and e["args"].get("trace_id") == failover_trace}
        assert lanes_with_trace == {0, 1}
    finally:
        fleet.stop()
        fe.stop()
        s1.stop()
        s2.stop()


def test_smoke_chaos_tool():
    """Fast variant of tools/smoke_chaos.py: kill/recover failover plus a
    canary rollback end-to-end in one run."""
    import tools.smoke_chaos as smoke
    out = smoke.run(n_requests=6)
    assert out["kill_phase_errors"] == 0
    assert out["breaker_opened"] is True
    assert out["recovered_replicas"] == ["a", "b"]
    assert out["canary_outcome"] == "rolled_back"
    assert out["client_5xx"] == 0
