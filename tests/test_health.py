"""Health & alerting layer tests: structured logging (ring buffer, trace
correlation, sinks, level counter), HealthMonitor aggregation + deep
/healthz on both servers, the AlertEngine rule lifecycle under ManualClock
(pending -> firing -> resolved, webhook exactly once per transition),
TrainingHealthListener watchdog (NaN/divergence/step-time) with
FaultTolerantTrainer checkpoint-and-halt, and the satellite regressions
(send_json NaN sanitization, PerformanceListener None-until-measured,
raising gauge callbacks surviving the scrape)."""
import io
import json
import math
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.telemetry import (AlertEngine, AlertRule,
                                          HealthMonitor, LogBuffer,
                                          MetricsRegistry, StderrJsonSink,
                                          StructuredLogger, Tracer,
                                          WebhookAlertSink,
                                          default_serving_rules,
                                          default_training_rules,
                                          render_prometheus)
from deeplearning4j_tpu.telemetry.alerts import RouterAlertSink
from deeplearning4j_tpu.util.http import (BackgroundHttpServer, QuietHandler,
                                          dumps_safe)
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


def _http_get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ------------------------------------------------------------------ logging

def test_structured_log_record_shape_and_counter(manual_clock):
    reg = MetricsRegistry()
    log = StructuredLogger(name="test", registry=reg)
    rec = log.info("hello", a=1)
    assert rec["time"] == pytest.approx(1000.0)
    assert rec["level"] == "info" and rec["logger"] == "test"
    assert rec["fields"] == {"a": 1}
    assert "trace_id" not in rec            # no active span
    log.error("boom")
    assert reg.get("log_events_total").get(level="info") == 1
    assert reg.get("log_events_total").get(level="error") == 1
    assert reg.get("log_events_total").get() == 2


def test_log_trace_correlation_from_current_span():
    log = StructuredLogger(name="t", registry=MetricsRegistry())
    tracer = Tracer()
    with tracer.span("request") as root:
        with tracer.span("inner") as inner:
            rec = log.warning("within")
    assert rec["trace_id"] == root.trace_id
    assert rec["span_id"] == inner.span_id
    # filtering the buffer by that trace id finds exactly this record
    assert log.buffer.records(trace_id=root.trace_id) == [rec]


def test_log_buffer_ring_bound_and_level_filter():
    buf = LogBuffer(capacity=4)
    log = StructuredLogger(name="t", buffer=buf, registry=MetricsRegistry())
    for i in range(6):
        log.log("debug" if i % 2 else "error", f"m{i}")
    assert buf.total == 6 and buf.dropped == 2
    msgs = [r["message"] for r in buf.records()]
    assert msgs == ["m2", "m3", "m4", "m5"]
    errors = [r["message"] for r in buf.records(level="error")]
    assert errors == ["m2", "m4"]
    assert [r["message"] for r in buf.records(n=1)] == ["m5"]
    assert buf.records(n=0) == []       # n=0 means zero, not "everything"
    assert buf.records(n=-3) == []


def test_log_sinks_stderr_file_and_dead_sink(tmp_path):
    stream = io.StringIO()
    from deeplearning4j_tpu.telemetry import FileJsonSink
    path = tmp_path / "log.jsonl"
    fsink = FileJsonSink(path)

    def dead_sink(record):
        raise RuntimeError("sink down")

    log = StructuredLogger(name="t", registry=MetricsRegistry(),
                           sinks=[StderrJsonSink(stream), fsink, dead_sink])
    log.info("one", loss=float("nan"))     # non-finite field -> null in JSON
    log.info("two")
    fsink.close()
    assert log.sink_errors == 2            # dead sink never broke the caller
    lines = [json.loads(l) for l in stream.getvalue().splitlines()]
    assert [l["message"] for l in lines] == ["one", "two"]
    assert lines[0]["fields"]["loss"] is None
    disk = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["message"] for l in disk] == ["one", "two"]


def test_logger_level_floor_and_child():
    log = StructuredLogger(name="root", registry=MetricsRegistry(),
                           level="warning")
    assert log.debug("nope") is None and log.info("nope") is None
    assert log.warning("yes")["level"] == "warning"
    child = log.child("sub")
    child.error("from child")
    names = [(r["logger"], r["message"]) for r in log.buffer.records()]
    assert names == [("root", "yes"), ("root.sub", "from child")]


# ------------------------------------------------------------------- health

def test_health_monitor_aggregates_worst_status():
    m = HealthMonitor()
    assert m.check()["status"] == "healthy"      # vacuous
    m.register("a", lambda: "healthy")
    m.register("b", lambda: ("degraded", {"queue": 9}))
    rep = m.check()
    assert rep["status"] == "degraded"
    assert rep["components"]["b"] == {"status": "degraded", "queue": 9}
    assert HealthMonitor.http_status(rep) == 200  # degraded still serves
    m.set_status("c", "unhealthy", reason="down")
    rep = m.check()
    assert rep["status"] == "unhealthy"
    assert HealthMonitor.http_status(rep) == 503
    m.set_status("c", "healthy")                  # push-style update in place
    m.unregister("b")
    assert m.check()["status"] == "healthy"


def test_health_probe_exception_is_unhealthy_not_a_crash():
    m = HealthMonitor()
    m.register("broken", lambda: 1 / 0)
    rep = m.check()
    assert rep["components"]["broken"]["status"] == "unhealthy"
    assert "ZeroDivisionError" in rep["components"]["broken"]["error"]


def test_health_transitions_logged():
    log = StructuredLogger(name="t", registry=MetricsRegistry())
    m = HealthMonitor(logger=log)
    state = {"status": "healthy"}
    m.register("comp", lambda: state["status"])
    m.check()
    state["status"] = "unhealthy"
    m.check()
    m.check()                                   # steady state: no new record
    recs = [r for r in log.buffer.records()
            if r["message"] == "health_transition"]
    assert [r["fields"]["status"] for r in recs] == ["healthy", "unhealthy"]
    assert recs[-1]["level"] == "error"


# ------------------------------------------------------------------- alerts

def test_alert_threshold_lifecycle_under_manual_clock(manual_clock):
    reg = MetricsRegistry()
    depth = reg.gauge("queue_depth")
    events = []
    eng = AlertEngine(registry=reg, interval_s=0, sinks=[events.append])
    eng.add_rule(AlertRule("deep_queue", metric="queue_depth", threshold=100,
                           for_duration_s=30, severity="page"))
    depth.set(10)
    eng.evaluate()
    assert eng.state()["rules"][0]["state"] == "inactive"
    depth.set(500)
    eng.evaluate()                              # condition true -> pending
    assert eng.state()["rules"][0]["state"] == "pending"
    manual_clock.advance(10)
    eng.evaluate()                              # held 10s < 30s: still pending
    assert eng.state()["rules"][0]["state"] == "pending"
    assert events == []                         # pending never notifies
    manual_clock.advance(25)
    eng.evaluate()                              # held 35s >= 30s: fires
    st = eng.state()
    assert st["rules"][0]["state"] == "firing" and st["firing"] == 1
    assert [e["state"] for e in events] == ["firing"]
    eng.evaluate()                              # still firing: no re-notify
    assert len(events) == 1
    depth.set(5)
    eng.evaluate()                              # recovery -> resolved
    assert eng.state()["rules"][0]["state"] == "inactive"
    assert [e["state"] for e in events] == ["firing", "resolved"]
    assert events[0]["rule"] == "deep_queue"
    assert events[0]["value"] == 500.0


def test_alert_pending_that_recovers_never_notifies(manual_clock):
    reg = MetricsRegistry()
    g = reg.gauge("g")
    events = []
    eng = AlertEngine(registry=reg, interval_s=0, sinks=[events.append])
    eng.add_rule(AlertRule("flap", metric="g", threshold=1,
                           for_duration_s=60))
    g.set(5)
    eng.evaluate()
    manual_clock.advance(10)
    g.set(0)
    eng.evaluate()                              # recovered inside for_duration
    assert events == []
    assert eng.state()["rules"][0]["state"] == "inactive"


def test_alert_ratio_rule_windows_counter_deltas(manual_clock):
    reg = MetricsRegistry()
    errs, reqs = reg.counter("errors_total"), reg.counter("requests_total")
    eng = AlertEngine(registry=reg, interval_s=0)
    eng.add_rule(AlertRule("err", "ratio", numerator="errors_total",
                           denominator="requests_total", threshold=0.1,
                           window_s=60))
    reqs.inc(1000)                   # pre-engine history must not alert
    eng.evaluate()
    assert eng.state()["rules"][0]["state"] == "inactive"
    manual_clock.advance(10)
    reqs.inc(100)
    errs.inc(50)                     # 50% of the last window's traffic
    eng.evaluate()
    row = eng.state()["rules"][0]
    assert row["state"] == "firing" and row["value"] == pytest.approx(0.5)
    # window slides past the burst: clean traffic resolves it
    manual_clock.advance(120)
    reqs.inc(400)
    eng.evaluate()
    assert eng.state()["rules"][0]["state"] == "inactive"


def test_alert_burn_rate_rule(manual_clock):
    reg = MetricsRegistry()
    errs, reqs = reg.counter("errors_total"), reg.counter("requests_total")
    eng = AlertEngine(registry=reg, interval_s=0)
    eng.add_rule(AlertRule("burn", "burn_rate", numerator="errors_total",
                           denominator="requests_total", slo=0.999,
                           threshold=14.4, window_s=300))
    eng.evaluate()
    manual_clock.advance(30)
    reqs.inc(1000)
    errs.inc(2)                      # 0.2% errors / 0.1% budget = 2x: ok
    eng.evaluate()
    assert eng.state()["rules"][0]["state"] == "inactive"
    manual_clock.advance(30)
    reqs.inc(1000)
    errs.inc(50)                     # ~1.7% over window / 0.1% budget = 17x
    eng.evaluate()
    row = eng.state()["rules"][0]
    assert row["state"] == "firing" and row["value"] > 14.4


def test_alert_histogram_rule_aggregates_across_label_sets(manual_clock):
    """A labels-free threshold rule must see labeled observations too: the
    ETL pipelines record etl_consumer_wait_ms under pipeline=<name>, and
    default_training_rules' starvation rule names no labels."""
    reg = MetricsRegistry()
    h = reg.histogram("etl_consumer_wait_ms")
    for _ in range(20):
        h.observe(10_000.0, pipeline="train")
    eng = AlertEngine(registry=reg, interval_s=0)
    eng.add_rule(default_training_rules()[2])      # etl_consumer_starvation
    eng.evaluate()
    row = next(r for r in eng.state()["rules"]
               if r["name"] == "etl_consumer_starvation")
    assert row["state"] == "firing" and row["value"] == 10_000.0


def test_alert_rule_json_round_trip_and_validation():
    rules = default_serving_rules() + default_training_rules()
    for r in rules:
        clone = AlertRule.from_dict(json.loads(json.dumps(r.to_dict())))
        assert clone.to_dict() == r.to_dict()
    with pytest.raises(ValueError):
        AlertRule("bad", "ratio", numerator="a", threshold=1)  # no denominator
    with pytest.raises(ValueError):
        AlertRule("bad", "burn_rate", numerator="a", denominator="b",
                  threshold=1, slo=2.0)
    with pytest.raises(ValueError):
        AlertRule("bad", metric="m", threshold=1, op="~")


def test_alert_missing_metric_is_no_data_not_firing(manual_clock):
    eng = AlertEngine(registry=MetricsRegistry(), interval_s=0)
    eng.add_rule(AlertRule("ghost", metric="does_not_exist", threshold=0,
                           op=">="))
    eng.evaluate()
    row = eng.state()["rules"][0]
    assert row["state"] == "inactive" and row["value"] is None


class _WebhookReceiver(BackgroundHttpServer):
    def __init__(self):
        super().__init__()
        self.events = []

    def start(self):
        recv = self

        class Handler(QuietHandler):
            def do_POST(self):
                recv.events.append(json.loads(self.body()))
                self.send_json(200, {"ok": True})

        return self.start_with(Handler)


def test_webhook_sink_fires_exactly_once_per_transition(manual_clock):
    reg = MetricsRegistry()
    g = reg.gauge("pressure")
    receiver = _WebhookReceiver().start()
    try:
        sink = WebhookAlertSink(receiver.url + "/alert")
        eng = AlertEngine(registry=reg, interval_s=0, sinks=[sink])
        eng.add_rule(AlertRule("pressure_high", metric="pressure",
                               threshold=10, for_duration_s=5))
        g.set(99)
        eng.evaluate()                          # pending: no webhook
        assert receiver.events == []
        manual_clock.advance(5)
        eng.evaluate()                          # firing: one POST
        eng.evaluate()                          # steady firing: none
        g.set(0)
        eng.evaluate()                          # resolved: one POST
        eng.evaluate()                          # steady inactive: none
        assert [e["state"] for e in receiver.events] == ["firing", "resolved"]
        assert all(e["rule"] == "pressure_high" for e in receiver.events)
        assert sink.delivered == 2
    finally:
        receiver.stop()


def test_replacing_or_removing_a_firing_rule_resolves_it(manual_clock):
    """The receiver of a firing event holds an open incident: replacing or
    removing that rule must still deliver the closing resolved event."""
    reg = MetricsRegistry()
    g = reg.gauge("g")
    events = []
    eng = AlertEngine(registry=reg, interval_s=0, sinks=[events.append])
    eng.add_rule(AlertRule("r", metric="g", threshold=1))
    g.set(5)
    eng.evaluate()
    assert [e["state"] for e in events] == ["firing"]
    eng.add_rule(AlertRule("r", metric="g", threshold=100))  # raise threshold
    assert [e["state"] for e in events] == ["firing", "resolved"]
    g.set(500)
    eng.evaluate()
    assert [e["state"] for e in events][-1] == "firing"
    eng.remove_rule("r")
    assert [e["state"] for e in events] == ["firing", "resolved",
                                            "firing", "resolved"]


def test_post_json_tolerates_non_json_ack():
    """A webhook answering 200 with a plain-text body ("ok", Slack-style)
    is a delivered alert, not a sink error."""
    class TextReceiver(BackgroundHttpServer):
        def start(self):
            class Handler(QuietHandler):
                def do_POST(self):
                    self.send_text(200, "ok")
            return self.start_with(Handler)

    from deeplearning4j_tpu.util.http import post_json
    r = TextReceiver().start()
    try:
        assert post_json(r.url + "/hook", {"a": 1}) == "ok"
    finally:
        r.stop()


def test_router_alert_sink_posts_telemetry_reports(manual_clock):
    from deeplearning4j_tpu.ui.storage import CollectionStatsStorageRouter
    reg = MetricsRegistry()
    g = reg.gauge("g")
    router = CollectionStatsStorageRouter()
    eng = AlertEngine(registry=reg, interval_s=0,
                      sinks=[RouterAlertSink(router, session_id="s1")])
    eng.add_rule(AlertRule("r", metric="g", threshold=1))
    g.set(2)
    eng.evaluate()
    assert len(router.updates) == 1
    d = router.updates[0]
    assert d["type"] == "telemetry" and d["session_id"] == "s1"
    assert d["alert"]["rule"] == "r" and d["alert"]["state"] == "firing"


# ------------------------------------------------- satellite regressions

def test_send_json_sanitizes_non_finite_floats():
    assert json.loads(dumps_safe({"a": float("nan")})) == {"a": None}
    out = json.loads(dumps_safe(
        {"v": [1.5, float("inf"), float("-inf")], "ok": "s"}))
    assert out == {"v": [1.5, None, None], "ok": "s"}
    # strict decoders (JSON.parse semantics) accept the emitted text
    assert "NaN" not in dumps_safe({"a": float("nan")})


def test_performance_listener_reports_none_until_first_measurement(
        manual_clock):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener
    pl = PerformanceListener(log_fn=lambda *a: None)
    # a snapshot before any measured interval must serialize cleanly
    snap = {"samples_per_sec": pl.last_samples_per_sec,
            "iteration_ms": pl.last_iteration_ms,
            "batches_per_sec": pl.last_batches_per_sec}
    assert json.loads(dumps_safe(snap)) == {
        "samples_per_sec": None, "iteration_ms": None,
        "batches_per_sec": None}
    model = types.SimpleNamespace(score_value=0.5)
    pl.iteration_done(model, 1)
    manual_clock.advance(0.5)
    pl.record_batch_size(64)
    pl.iteration_done(model, 2)
    assert pl.last_iteration_ms == pytest.approx(500.0)
    assert pl.last_samples_per_sec == pytest.approx(128.0)


def test_raising_gauge_callback_survives_scrape_and_logs():
    reg = MetricsRegistry()
    reg.counter("good_total").inc(3)
    reg.gauge("bad_gauge", fn=lambda: 1 / 0)
    reg.gauge("good_gauge").set(7)
    text = render_prometheus(reg)               # must not raise
    assert "good_total 3" in text
    assert "good_gauge 7" in text
    sample_lines = [l for l in text.splitlines() if not l.startswith("#")]
    assert not any(l.startswith("bad_gauge") for l in sample_lines)
    assert reg.get("bad_gauge").get() is None   # point read degrades too
    from deeplearning4j_tpu.telemetry import get_logger
    recs = [r for r in get_logger().buffer.records()
            if r["message"] == "gauge_callback_error"
            and r["fields"]["metric"] == "bad_gauge"]
    assert recs and "ZeroDivisionError" in recs[-1]["fields"]["error"]


def test_raising_gauge_logs_to_the_owning_registrys_logger():
    """A registry wired with its own logger (a ServingServer does this)
    keeps gauge-callback errors on ITS /logs, not the process buffer."""
    reg = MetricsRegistry()
    log = StructuredLogger(name="srv", registry=reg)
    reg.logger = log
    reg.gauge("local_bad", fn=lambda: 1 / 0)
    assert render_prometheus(reg)       # scrape survives
    recs = [r for r in log.buffer.records()
            if r["message"] == "gauge_callback_error"]
    assert recs and recs[-1]["fields"]["metric"] == "local_bad"
    assert reg.get("log_events_total").get(level="warning") >= 1


# ---------------------------------------------- training watchdog

def _fake_model(loss):
    return types.SimpleNamespace(score_value=loss, last_gradients=None)


def test_training_health_listener_nan_loss(manual_clock):
    reg = MetricsRegistry()
    m = HealthMonitor()
    log = StructuredLogger(name="t", registry=reg)
    from deeplearning4j_tpu.optimize.listeners import TrainingHealthListener
    w = TrainingHealthListener(health=m, registry=reg, logger=log)
    w.iteration_done(_fake_model(0.7), 1)
    assert m.check()["status"] == "healthy"
    assert not w.should_halt
    w.iteration_done(_fake_model(float("nan")), 2)
    assert w.should_halt and w.trip_reason == "nan_loss"
    assert reg.get("training_nan_total").get() == 1
    # a PERSISTENT NaN (nothing halts under plain model.fit) is one
    # detection: no per-iteration counter inflation or /logs ring eviction
    for i in range(3, 20):
        w.iteration_done(_fake_model(float("nan")), i)
    assert reg.get("training_nan_total").get() == 1
    assert len([r for r in log.buffer.records()
                if r["message"] == "training_nan_loss"]) == 1
    rep = m.check()
    assert rep["components"]["trainer"]["status"] == "unhealthy"
    assert rep["components"]["trainer"]["reason"] == "nan_loss"
    recs = [r for r in log.buffer.records()
            if r["message"] == "training_nan_loss"]
    assert recs and recs[0]["level"] == "error"


def test_training_health_listener_divergence(manual_clock):
    reg = MetricsRegistry()
    from deeplearning4j_tpu.optimize.listeners import TrainingHealthListener
    w = TrainingHealthListener(registry=reg,
                               logger=StructuredLogger(registry=reg),
                               divergence_factor=10.0, divergence_margin=0.5,
                               divergence_patience=3)
    it = 0
    for loss in (1.0, 0.5, 0.4):
        it += 1
        w.iteration_done(_fake_model(loss), it)
    assert w.best_loss == pytest.approx(0.4)
    for loss in (50.0, 60.0):            # two diverged iterations: patience
        it += 1
        w.iteration_done(_fake_model(loss), it)
    assert not w.should_halt
    it += 1
    w.iteration_done(_fake_model(70.0), it)   # third in a row trips
    assert w.should_halt and w.trip_reason == "divergence"
    assert reg.get("training_divergence_total").get() == 1


def test_training_health_listener_divergence_streak_resets(manual_clock):
    reg = MetricsRegistry()
    from deeplearning4j_tpu.optimize.listeners import TrainingHealthListener
    w = TrainingHealthListener(registry=reg,
                               logger=StructuredLogger(registry=reg),
                               divergence_patience=3)
    losses = [1.0, 50.0, 60.0, 1.2, 50.0, 55.0]   # never 3 in a row
    for i, loss in enumerate(losses, 1):
        w.iteration_done(_fake_model(loss), i)
    assert not w.should_halt


def test_training_health_listener_nan_gradient(manual_clock):
    reg = MetricsRegistry()
    from deeplearning4j_tpu.optimize.listeners import TrainingHealthListener
    w = TrainingHealthListener(registry=reg,
                               logger=StructuredLogger(registry=reg),
                               check_gradients=True)
    assert w.wants_gradients            # keeps grads alive on the model
    model = _fake_model(0.5)
    model.last_gradients = {"w": np.array([0.1, 0.2])}
    w.iteration_done(model, 1)
    assert not w.should_halt
    model.last_gradients = {"w": np.array([0.1, np.nan])}
    w.iteration_done(model, 2)
    assert w.should_halt and w.trip_reason == "nan_gradient"


def test_training_health_listener_step_time_regression(manual_clock):
    reg = MetricsRegistry()
    from deeplearning4j_tpu.optimize.listeners import TrainingHealthListener
    w = TrainingHealthListener(registry=reg,
                               logger=StructuredLogger(registry=reg),
                               step_time_factor=3.0, step_time_window=4)
    m = _fake_model(0.5)
    it = 0
    for _ in range(5):                  # 1 warm-up + 4 baseline @100ms
        it += 1
        w.iteration_done(m, it)
        manual_clock.advance(0.1)
    for _ in range(4):                  # 4 recent @500ms -> 5x baseline
        it += 1
        w.iteration_done(m, it)
        manual_clock.advance(0.5)
    it += 1
    w.iteration_done(m, it)
    assert w.step_time_regressed
    assert reg.get("training_step_time_regressions_total").get() == 1
    assert not w.should_halt            # regression degrades, never halts
    assert w._probe()[0] == "degraded"


def test_fault_tolerant_trainer_checkpoints_and_halts_on_nan(tmp_path,
                                                            manual_clock):
    from tools.smoke_telemetry import _tiny_net
    from deeplearning4j_tpu import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.optimize.listeners import (TrainingHalted,
                                                       TrainingHealthListener)
    from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer
    reg = MetricsRegistry()
    monitor = HealthMonitor()
    w = TrainingHealthListener(health=monitor, registry=reg,
                               logger=StructuredLogger(registry=reg))
    X = np.random.default_rng(0).normal(size=(24, 6)).astype(np.float32)
    X[10, 0] = np.nan                   # second batch of 8 is poisoned
    Y = np.eye(3, dtype=np.float32)[np.arange(24) % 3]
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(lambda: _tiny_net(),
                                   CheckpointConfig(tmp_path, frequency=1),
                                   health=w)
    with pytest.raises(TrainingHalted) as exc:
        trainer.fit(it, epochs=1)
    assert exc.value.reason == "nan_loss" and exc.value.iteration == 2
    # checkpoint-and-halt: the blown-up state is QUARANTINED under halt-*
    # (forensics), never part of the resumable ckpt-* chain
    assert (tmp_path / "halt-000000002").is_dir()
    assert exc.value.checkpoint_path == str(tmp_path / "halt-000000002")
    assert monitor.check()["components"]["trainer"]["status"] == "unhealthy"
    # restart resumes from the last PRE-blow-up periodic checkpoint, so a
    # fixed run never restores NaN params
    resumed = FaultTolerantTrainer(lambda: _tiny_net(),
                                   CheckpointConfig(tmp_path, frequency=1))
    assert resumed.resumed and resumed.state["iteration"] == 1
    assert np.all(np.isfinite(np.asarray(resumed.model.get_flat_params())))


# ---------------------------------------------- endpoints (UI server)

def test_ui_server_health_alerts_logs_endpoints(manual_clock):
    from deeplearning4j_tpu.ui.server import UIServer
    reg = MetricsRegistry()
    monitor = HealthMonitor()
    logger = StructuredLogger(name="ui-test", registry=reg)
    engine = AlertEngine(registry=reg, interval_s=0)
    engine.add_rule(AlertRule("g_high", metric="g", threshold=1))
    server = UIServer(port=0, health=monitor, alerts=engine, logger=logger)
    server.start()
    try:
        status, h = _http_get(server.url + "/healthz")
        assert status == 200 and h["status"] == "healthy"
        monitor.register("etl:bad", lambda: ("unhealthy", {"reason": "x"}))
        status, h = _http_get(server.url + "/healthz")
        assert status == 503
        assert h["components"]["etl:bad"]["reason"] == "x"
        reg.gauge("g").set(5)
        engine.evaluate()
        status, a = _http_get(server.url + "/alerts")
        assert status == 200
        assert a["rules"][0]["name"] == "g_high"
        assert a["rules"][0]["state"] == "firing" and a["firing"] == 1
        logger.info("hello", nan=float("nan"))
        status, l = _http_get(server.url + "/logs?n=10")
        assert status == 200
        assert any(r["message"] == "hello" for r in l["records"])
        status, err = _http_get(server.url + "/logs?n=all")
        assert status == 400 and "bad query" in err["error"]
        # free-form fields may hold non-JSON-native objects (numpy scalars,
        # exceptions): /logs stringifies instead of dropping the connection
        logger.info("odd", version=np.int64(3), err=ValueError("boom"))
        status, l = _http_get(server.url + "/logs?n=1")
        assert status == 200
        assert l["records"][0]["fields"] == {"version": "3", "err": "boom"}
    finally:
        server.stop()


def test_etl_pipeline_registers_health_probe(manual_clock):
    from deeplearning4j_tpu.etl import ParallelPipelineExecutor
    monitor = HealthMonitor()

    class _Reader:
        def __init__(self, n=8):
            self.n, self.i = n, 0

        def has_next(self):
            return self.i < self.n

        def next_record(self):
            self.i += 1
            if self.i == 5:
                raise ValueError("corrupt record")
            return [float(self.i)]

        def reset(self):
            self.i = 0

    pipe = ParallelPipelineExecutor(_Reader(), batch_size=2, workers=1,
                                    name="probe-test", health=monitor,
                                    registry=MetricsRegistry(),
                                    tracer=Tracer(enabled=False))
    assert "etl:probe-test" in monitor.components()
    with pytest.raises(ValueError):
        while pipe.has_next():          # reader blows up mid-stream
            pipe.next()
    pipe.close()                        # error already surfaced: clean close
    assert "etl:probe-test" not in monitor.components()
    # a pipeline whose consumer STOPPED pulling: the monitor sees the parked
    # error through the probe before anyone claims it
    pipe2 = ParallelPipelineExecutor(_Reader(), batch_size=2, workers=1,
                                     name="probe-test", health=monitor,
                                     registry=MetricsRegistry(),
                                     tracer=Tracer(enabled=False))
    import time
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        rep = monitor.check()["components"]["etl:probe-test"]
        if rep["status"] == "unhealthy":
            break
    assert rep["status"] == "unhealthy", rep
    with pytest.raises(ValueError):
        pipe2.close()                   # close surfaces the parked error...
    assert "etl:probe-test" not in monitor.components()  # ...and unregisters


def test_etl_pipelines_sharing_a_name_get_distinct_probes(manual_clock):
    from deeplearning4j_tpu.etl import ParallelPipelineExecutor
    monitor = HealthMonitor()

    class _Reader:
        def __init__(self):
            self.i = 0

        def has_next(self):
            return self.i < 4

        def next_record(self):
            self.i += 1
            return [1.0]

        def reset(self):
            self.i = 0

    kw = dict(batch_size=2, workers=1, health=monitor,
              registry=MetricsRegistry(), tracer=Tracer(enabled=False))
    a = ParallelPipelineExecutor(_Reader(), name="etl", **kw)
    b = ParallelPipelineExecutor(_Reader(), name="etl", **kw)
    assert monitor.components() == ["etl:etl", "etl:etl-2"]
    a.close()
    assert monitor.components() == ["etl:etl-2"]   # b's probe survives
    # close -> reset re-registers a's coverage under a FRESH unique key
    # (never adopting b's), and a's next close leaves b's probe alone
    a.reset()
    assert sorted(monitor.components()) == ["etl:etl", "etl:etl-2"]
    a.close()
    assert monitor.components() == ["etl:etl-2"]
    b.close()
    assert monitor.components() == []


# ---------------------------------------------- acceptance + smoke tool

def test_acceptance_nan_run_alerts_healthz_logs_trace_correlated(
        tmp_path, manual_clock):
    """ISSUE 4 acceptance: a NaN-loss training run fires an alert at
    GET /alerts, flips deep /healthz to 503 with the trainer unhealthy, and
    the structured /logs records carry trace ids matching the training
    iteration spans — all under ManualClock, zero wall-clock sleeps."""
    from tools.smoke_telemetry import _tiny_net
    from deeplearning4j_tpu import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.optimize.listeners import (TrainingHalted,
                                                       TrainingHealthListener)
    from deeplearning4j_tpu.serving import ServingServer
    from deeplearning4j_tpu.telemetry import get_tracer
    from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer

    tracer = get_tracer()
    was_enabled, tracer.enabled = tracer.enabled, True
    server = ServingServer(_tiny_net(), max_batch_size=8,
                           alert_interval_s=0).start()
    try:
        for rule in default_training_rules():
            server.alerts.add_rule(rule)
        watchdog = TrainingHealthListener(health=server.health,
                                          registry=server.metrics.registry,
                                          logger=server.logger)
        X = np.random.default_rng(1).normal(size=(16, 6)).astype(np.float32)
        X[0, 0] = np.nan
        Y = np.eye(3, dtype=np.float32)[np.arange(16) % 3]
        trainer = FaultTolerantTrainer(
            lambda: _tiny_net(), CheckpointConfig(tmp_path, frequency=0),
            health=watchdog)
        with pytest.raises(TrainingHalted):
            trainer.fit(ListDataSetIterator(DataSet(X, Y), batch_size=8),
                        epochs=1)
        server.alerts.evaluate()

        status, alerts = _http_get(server.url + "/alerts")
        firing = {r["name"] for r in alerts["rules"]
                  if r["state"] == "firing"}
        assert "training_nan" in firing, alerts

        status, h = _http_get(server.url + "/healthz")
        assert status == 503, h
        assert h["health"] == "unhealthy"
        assert h["components"]["trainer"]["status"] == "unhealthy"
        assert h["components"]["trainer"]["reason"] == "nan_loss"

        status, logs = _http_get(server.url + "/logs?level=error")
        nan_recs = [r for r in logs["records"]
                    if r["message"] == "training_nan_loss"]
        assert nan_recs
        iteration_traces = {s.trace_id for s in tracer.finished_spans()
                            if s.name == "iteration"}
        assert all(r["trace_id"] in iteration_traces for r in nan_recs)
    finally:
        server.stop()
        tracer.enabled = was_enabled
        tracer.clear()


def test_smoke_health_tool():
    """tools/smoke_health.py end to end (fast, like the other smoke
    harnesses): healthy baseline, injected-probe 503, NaN halt, firing
    alert, trace-correlated logs."""
    import tools.smoke_health as smoke
    out = smoke.run()
    assert out["firing"] == ["training_nan"]
    assert out["halt_reason"] == "nan_loss"
    assert out["nan_log_records"] >= 1
