"""Concurrency analysis tests: the GL018/GL019/GL020 whole-program rules
(lockset inference edges: with-vs-acquire/release, RLock re-entry, locks
passed to helpers, callback references, external locks), the shared GL003
annotation channel, the runtime lock sanitizer (ManualClock-driven — zero
real sleeps), the --baseline-prune CLI, and the repo-wide gate: the whole
package + tools/ must produce ZERO new concurrency findings inside a 5s
wall-time budget."""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time
from pathlib import Path

import pytest

from deeplearning4j_tpu.analysis import Analyzer, Baseline, get_rule

REPO = Path(__file__).resolve().parents[1]
BASELINE_PATH = REPO / "tools" / "lint_baseline.json"

CONCURRENCY_RULES = ("GL018", "GL019", "GL020")


def lint(src, rules, rel_path="deeplearning4j_tpu/pkg/mod.py"):
    analyzer = Analyzer(rules=[get_rule(r) for r in rules], root=str(REPO))
    violations, err = analyzer.analyze_source(textwrap.dedent(src), rel_path)
    assert err is None, err
    return violations


# ---------------------------------------------------------------------------
# GL018 — unguarded-shared-write
# ---------------------------------------------------------------------------

def test_gl018_locked_write_then_lockfree_read():
    violations = lint("""\
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0

        def add(self, n):
            with self._lock:
                self.total += n

        def snapshot(self):
            return {"total": self.total}
    """, rules=["GL018"])
    assert [(v.rule, v.line) for v in violations] == [("GL018", 13)]
    assert "self.total is written under self._lock in add()" \
        in violations[0].message
    assert "guarded by: none" in violations[0].message   # actionable fix


def test_gl018_guarded_by_none_declares_intent():
    # `# guarded by: none` is the explicit copy-on-write/monotonic-read
    # channel: the writer stays serialized, readers are declared lock-free
    violations = lint("""\
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []   # guarded by: none

        def add(self, x):
            with self._lock:
                self.items = self.items + [x]

        def read(self):
            return list(self.items)
    """, rules=["GL018"])
    assert violations == []


def test_gl018_annotation_on_multiline_declaration():
    # the annotation may sit on ANY line of a multi-line declaration
    # (closing bracket included), not just the statement's first line
    violations = lint("""\
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = [
                0,
            ]   # guarded by: none

        def add(self, x):
            with self._lock:
                self.items = self.items + [x]

        def read(self):
            return list(self.items)
    """, rules=["GL018"])
    assert violations == []


def test_gl018_guarded_by_lock_routes_to_gl003():
    # an explicit `# guarded by: self._lock` moves the attribute to GL003's
    # annotation channel — GL018 must not double-report it
    src = """\
    import threading

    class Stats:
        def __init__(self):
            self.total = 0   # guarded by: self._lock
            self._lock = threading.Lock()

        def add(self, n):
            with self._lock:
                self.total += n

        def snapshot(self):
            return {"total": self.total}
    """
    assert lint(src, rules=["GL018"]) == []
    gl003 = lint(src, rules=["GL003"])
    assert [(v.rule, v.line) for v in gl003] == [("GL003", 13)]


def test_gl018_lock_passed_to_helper_binds_param():
    # self._helper(self._lock) + `with lock:` in the helper resolves the
    # parameter to the lock attribute, so the helper's write counts as
    # locked and the lock-free reader is the one flagged
    violations = lint("""\
    import threading

    class Owner:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            self._helper(self._lock)

        def _helper(self, lock):
            with lock:
                self.n += 1

        def read(self):
            return self.n
    """, rules=["GL018"])
    assert [(v.rule, v.line) for v in violations] == [("GL018", 16)]


def test_gl018_callback_reference_counts_as_locked_call_site():
    # `self._retry.call(self._attempt, obj)` under the lock: the bare
    # method reference makes _attempt's accesses inherit the caller's
    # lockset (the streaming-broker retry idiom) — no false positive
    violations = lint("""\
    import threading

    class Retry:
        def call(self, fn, obj):
            return fn(obj)

    class Client:
        def __init__(self):
            self._lock = threading.Lock()
            self._retry = Retry()
            self._sock = None

        def request(self, obj):
            with self._lock:
                return self._retry.call(self._attempt, obj)

        def close(self):
            with self._lock:
                self._sock = None

        def _attempt(self, obj):
            self._sock = obj
            return self._sock
    """, rules=["GL018"])
    assert violations == []


def test_gl018_acquire_release_form_counts_as_locked():
    # lockset tracking follows acquire()/release() (try/finally form) the
    # same as `with` blocks
    violations = lint("""\
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0

        def add(self, n):
            self._lock.acquire()
            try:
                self.total += n
            finally:
                self._lock.release()

        def snapshot(self):
            return self.total
    """, rules=["GL018"])
    assert [(v.rule, v.line) for v in violations] == [("GL018", 16)]


# ---------------------------------------------------------------------------
# GL019 — blocking-under-lock
# ---------------------------------------------------------------------------

def test_gl019_sleep_under_with():
    violations = lint("""\
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self):
            with self._lock:
                time.sleep(1.0)
    """, rules=["GL019"])
    assert [(v.rule, v.line) for v in violations] == [("GL019", 10)]
    assert "time.sleep() blocks while holding self._lock" \
        in violations[0].message


def test_gl019_blocking_reached_through_helper():
    # acquire/try/finally in the caller, sleep in a private helper: flagged
    # once, at the lock-holding call site (propagation through the call
    # graph), not inside the helper — the helper is innocent on its own
    violations = lint("""\
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self):
            self._lock.acquire()
            try:
                self._sweep()
            finally:
                self._lock.release()

        def _sweep(self):
            time.sleep(0.5)
    """, rules=["GL019"])
    assert [(v.rule, v.line) for v in violations] == [("GL019", 11)]
    assert "self._sweep() reaches blocking time.sleep() while holding " \
        "self._lock" in violations[0].message


def test_gl019_external_lock_attribute():
    # `with ctx.run_lock:` — a lock-ish attribute of a local — is held
    # state for blocking detection even though it is not a self-attribute
    # (the mesh dispatch shape)
    violations = lint("""\
    import jax

    class Dispatcher:
        def run(self, ctx, out):
            with ctx.run_lock:
                jax.block_until_ready(out)
    """, rules=["GL019"])
    assert [(v.rule, v.line) for v in violations] == [("GL019", 6)]
    assert "ctx.run_lock" in violations[0].message


def test_gl019_condition_wait_is_exempt():
    # Condition.wait releases the lock it waits on — NOT blocking-under-lock
    violations = lint("""\
    import threading

    class Q:
        def __init__(self):
            self._work = threading.Condition()
            self._items = []

        def take(self):
            with self._work:
                while not self._items:
                    self._work.wait()
                return self._items.pop()
    """, rules=["GL019"])
    assert violations == []


# ---------------------------------------------------------------------------
# GL020 — lock-order-inversion
# ---------------------------------------------------------------------------

def test_gl020_two_lock_cycle_reports_both_paths():
    violations = lint("""\
    import threading

    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
    """, rules=["GL020"])
    assert len(violations) == 2, violations
    assert sorted(v.line for v in violations) == [10, 15]
    # each edge report cites the counter-path closing the cycle
    for v in violations:
        assert "closes the cycle" in v.message


def test_gl020_plain_lock_reacquire_is_self_deadlock():
    violations = lint("""\
    import threading

    class Re:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self):
            with self._lock:
                self._inner()

        def _inner(self):
            with self._lock:
                pass
    """, rules=["GL020"])
    assert violations, "non-reentrant re-acquire must be flagged"
    assert any("re-acquires non-reentrant" in v.message or
               "closes the cycle" in v.message for v in violations)


def test_gl020_rlock_reentry_is_quiet():
    violations = lint("""\
    import threading

    class Re:
        def __init__(self):
            self._lock = threading.RLock()

        def outer(self):
            with self._lock:
                self._inner()

        def _inner(self):
            with self._lock:
                pass
    """, rules=["GL020"])
    assert violations == []


def test_gl020_consistent_order_is_quiet():
    violations = lint("""\
    import threading

    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
    """, rules=["GL020"])
    assert violations == []


# ---------------------------------------------------------------------------
# runtime lock sanitizer (ManualClock-driven: zero real sleeps)
# ---------------------------------------------------------------------------

@pytest.fixture
def sanitizer():
    from deeplearning4j_tpu.util.concurrency import lock_sanitizer
    from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                     TimeSourceProvider)
    clock = ManualClock(start_s=100.0)
    TimeSourceProvider.set_instance(clock)
    lock_sanitizer.reset()
    try:
        yield lock_sanitizer, clock
    finally:
        lock_sanitizer.uninstall()
        lock_sanitizer.reset()
        TimeSourceProvider.reset()


def test_sanitizer_inversion_fires_exactly_once(sanitizer):
    san, _ = sanitizer
    san.install()
    a, b = threading.Lock(), threading.Lock()
    assert type(a).__name__ == "SanitizedLock"
    with a:
        with b:
            pass
    for _ in range(3):          # opposite order, repeatedly
        with b:
            with a:
                pass
    rep = san.report()
    assert rep["by_kind"] == {"lock-order-inversion": 1}, rep
    v = san.table()["violations"][0]
    assert v["kind"] == "lock-order-inversion"
    assert set(v["locks"]) == {a.name, b.name}


def test_sanitizer_long_hold_fires_exactly_once_per_lock(sanitizer):
    san, clock = sanitizer
    san.install(long_hold_ms=50)
    lk = threading.Lock()
    for _ in range(2):
        lk.acquire()
        clock.advance(0.2)      # 200ms hold measured off the ManualClock
        lk.release()
    rep = san.report()
    assert rep["by_kind"] == {"long-hold": 1}, rep
    v = san.table()["violations"][0]
    assert v["held_ms"] == pytest.approx(200.0)
    assert v["limit_ms"] == 50.0


def test_sanitizer_rlock_reentry_and_consistent_order_are_clean(sanitizer):
    san, _ = sanitizer
    san.install()
    r = threading.RLock()
    with r:
        with r:
            pass
    a, b = threading.Lock(), threading.Lock()
    for _ in range(2):
        with a:
            with b:
                pass
    assert san.report()["violations"] == 0


def test_sanitizer_condition_protocol_round_trip(sanitizer):
    # Condition() built after install wraps a sanitized RLock; wait(0)
    # exercises _release_save/_acquire_restore with no second thread
    san, _ = sanitizer
    san.install()
    cv = threading.Condition()
    with cv:
        cv.wait(timeout=0)
    assert san.report()["violations"] == 0
    assert san.table()["held"] == {}


def test_sanitizer_uninstall_restores_plain_locks(sanitizer):
    san, _ = sanitizer
    orig = type(threading.Lock())
    san.install()
    assert type(threading.Lock()).__name__ == "SanitizedLock"
    san.uninstall()
    assert type(threading.Lock()) is orig


def test_sanitizer_env_gate(sanitizer):
    san, _ = sanitizer
    assert san.install_from_env(environ={}) is None
    assert not san.installed
    assert san.install_from_env(
        environ={"GRAFT_LOCK_SANITIZER": "1",
                 "GRAFT_LOCK_SANITIZER_LONG_HOLD_MS": "75"}) is san
    assert san.installed and san.long_hold_ms == 75.0


def test_sanitizer_table_is_json_serializable(sanitizer):
    san, clock = sanitizer
    san.install(long_hold_ms=10)
    a, b = threading.Lock(), threading.Lock()
    with a:
        clock.advance(0.05)
        with b:
            pass
    tbl = json.loads(json.dumps(san.table()))
    assert tbl["installed"] is True
    assert tbl["violations"] and tbl["edges"]
    assert tbl["locks_created"] >= 2


# ---------------------------------------------------------------------------
# CLI: --baseline-prune
# ---------------------------------------------------------------------------

BAD_CLASS = textwrap.dedent("""\
import time
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def work(self):
        with self._lock:
            time.sleep(1)
""")


def _lint_cli(root, baseline, *extra):
    return subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.analysis", "pkg",
         "--root", str(root), "--baseline", str(baseline), *extra],
        cwd=str(root), capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"})


def test_baseline_prune_round_trip(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    mod = pkg / "mod.py"
    baseline = tmp_path / "baseline.json"
    mod.write_text(BAD_CLASS)

    # seed the baseline from the violation, then FIX the code
    assert _lint_cli(tmp_path, baseline, "--baseline-update").returncode == 0
    entries = json.loads(baseline.read_text())["entries"]
    assert [e["rule"] for e in entries] == ["GL019"]
    mod.write_text(BAD_CLASS.replace(
        "            time.sleep(1)\n",
        "            pass\n        time.sleep(1)\n"))

    proc = _lint_cli(tmp_path, baseline, "--baseline-prune")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 stale entry removed" in proc.stdout
    assert json.loads(baseline.read_text())["entries"] == []
    # and the post-prune lint is clean (round trip)
    assert _lint_cli(tmp_path, baseline).returncode == 0


def test_baseline_prune_is_scoped_to_active_rules(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    mod = pkg / "mod.py"
    baseline = tmp_path / "baseline.json"
    mod.write_text(BAD_CLASS)
    assert _lint_cli(tmp_path, baseline, "--baseline-update").returncode == 0
    mod.write_text("x = 1\n")          # the GL019 finding is gone

    # prune with a DIFFERENT rule active: the GL019 entry is out of scope
    # and must be preserved verbatim
    proc = _lint_cli(tmp_path, baseline, "--baseline-prune",
                     "--rules", "GL018")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert [e["rule"] for e in json.loads(baseline.read_text())["entries"]] \
        == ["GL019"]

    # in-scope prune drops it
    proc = _lint_cli(tmp_path, baseline, "--baseline-prune")
    assert proc.returncode == 0
    assert json.loads(baseline.read_text())["entries"] == []


def test_baseline_prune_refuses_on_parse_errors(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(BAD_CLASS)
    baseline = tmp_path / "baseline.json"
    assert _lint_cli(tmp_path, baseline, "--baseline-update").returncode == 0
    (pkg / "mod.py").write_text("def broken(:\n")
    proc = _lint_cli(tmp_path, baseline, "--baseline-prune")
    assert proc.returncode == 1
    assert "NOT pruned" in proc.stdout
    assert [e["rule"] for e in json.loads(baseline.read_text())["entries"]] \
        == ["GL019"]


def test_baseline_update_and_prune_are_mutually_exclusive(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n")
    proc = _lint_cli(tmp_path, tmp_path / "b.json",
                     "--baseline-update", "--baseline-prune")
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# repo-wide gate + budget
# ---------------------------------------------------------------------------

def test_repo_concurrency_pass_is_clean_and_fast():
    """The gate: GL018/GL019/GL020 over the whole package + tools/ produce
    zero NEW findings (intentional remainders live in the committed,
    note-complete baseline) inside a 5s wall-time budget."""
    rules = [get_rule(r) for r in CONCURRENCY_RULES]
    t0 = time.monotonic()
    report = Analyzer(rules=rules, root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    wall = time.monotonic() - t0
    assert not report.errors, report.errors
    new, matched = Baseline.load(str(BASELINE_PATH)).split(report.violations)
    assert new == [], [str(v) for v in new]
    # every baselined concurrency finding carries an explanatory note
    noted = [e for e in Baseline.load(str(BASELINE_PATH)).entries
             if e["rule"] in CONCURRENCY_RULES]
    assert noted and all(e["note"].strip() for e in noted)
    assert wall < 5.0, f"concurrency pass took {wall:.2f}s (budget 5s)"
