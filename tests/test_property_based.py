"""Property-based tests (hypothesis) for the hand-rolled codecs and invariant
surfaces — the places where example-based tests under-cover the input space:
the HDF5 writer/reader, the streaming serde, the masked losses, the Viterbi
decoder, and the native CSV fast path's exact parity with the Python parser.
"""
import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from deeplearning4j_tpu.modelimport import hdf5_lite
from deeplearning4j_tpu.streaming.serde import serialize_array, deserialize_array


_names = st.text(alphabet=st.characters(min_codepoint=97, max_codepoint=122),
                 min_size=1, max_size=8)


@settings(max_examples=25, deadline=None)
@given(st.lists(st.tuples(_names,
                          st.integers(1, 4), st.integers(1, 6)),
                min_size=1, max_size=8, unique_by=lambda t: t[0]))
def test_hdf5_writer_reader_roundtrip_any_tree(specs):
    """Arbitrary group trees of float32 datasets survive the self-contained
    writer -> reader roundtrip exactly."""
    f = hdf5_lite.H5File()
    rng = np.random.default_rng(0)
    expected = {}
    for i, (name, ndim, dim) in enumerate(specs):
        shape = tuple(rng.integers(1, dim + 1) for _ in range(ndim))
        arr = rng.normal(size=shape).astype(np.float32)
        grp = f.create_group(f"g{i}")
        grp.create_dataset(name, arr)
        expected[(f"g{i}", name)] = arr
    import tempfile, os
    with tempfile.NamedTemporaryFile(suffix=".h5", delete=False) as tmp:
        path = tmp.name
    try:
        f.save(path)
        root = hdf5_lite.load(path)
        for (g, name), arr in expected.items():
            np.testing.assert_array_equal(root[g][name].value, arr)
    finally:
        os.unlink(path)


@settings(max_examples=30, deadline=None)
@given(st.sampled_from(["float32", "float64", "int32", "uint8"]),
       st.lists(st.integers(1, 5), min_size=1, max_size=3))
def test_streaming_serde_roundtrip_any_dtype_shape(dtype, shape):
    rng = np.random.default_rng(1)
    if dtype.startswith("float"):
        a = rng.normal(size=shape).astype(dtype)
    else:
        a = rng.integers(0, 100, size=shape).astype(dtype)
    b = deserialize_array(serialize_array(a))
    np.testing.assert_array_equal(a, b)
    assert b.dtype == np.dtype(dtype)


@settings(max_examples=30, deadline=None)
@given(st.integers(2, 5), st.integers(1, 24), st.floats(0.6, 0.99),
       st.floats(0.8, 0.999))
def test_viterbi_decode_invariants(states, frames, meta, pc):
    """Viterbi output is always a valid label sequence, and a constant
    observation sequence decodes to itself."""
    from deeplearning4j_tpu.util.viterbi import Viterbi
    v = Viterbi(np.arange(states), meta_stability=meta, p_correct=pc)
    rng = np.random.default_rng(states * frames)
    obs = rng.integers(0, states, frames)
    ll, path = v.decode(obs, binary_label_matrix=False)
    assert path.shape == (frames,)
    assert set(np.unique(path)).issubset(set(range(states)))
    assert ll <= 0.0
    const = np.full(frames, obs[0] if frames else 0)
    _, cpath = v.decode(const, binary_label_matrix=False)
    np.testing.assert_array_equal(cpath, const)


@settings(max_examples=30, deadline=None)
@given(st.integers(1, 6), st.integers(1, 5))
def test_masked_loss_all_ones_mask_equals_unmasked(b, f):
    """A mask of all ones must not change any loss value."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.losses import get_loss
    rng = np.random.default_rng(b * 10 + f)
    labels = jnp.asarray(np.eye(f)[rng.integers(0, f, b)].astype(np.float64)) \
        if f > 1 else jnp.asarray(rng.random((b, 1)))
    pre = jnp.asarray(rng.normal(size=(b, f)))
    for name, act in (("MSE", "identity"), ("L1", "identity"),
                      ("MCXENT", "softmax"), ("XENT", "sigmoid")):
        if name in ("MCXENT",) and f == 1:
            continue
        loss = get_loss(name)
        full = float(loss(labels, pre, act))
        masked = float(loss(labels, pre, act, jnp.ones((b,))))
        np.testing.assert_allclose(masked, full, rtol=1e-9, atol=1e-12)


@settings(max_examples=40, deadline=None)
@given(st.lists(st.lists(st.floats(-1e6, 1e6,
                                   allow_nan=False).map(lambda v: round(v, 4)),
                         min_size=1, max_size=5),
                min_size=1, max_size=6))
def test_native_csv_parity_with_python_float(rows):
    """Whenever the native CSV fast path accepts a buffer, its values must
    equal Python float() parsing exactly (float64 parity contract)."""
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    width = len(rows[0])
    rows = [r[:width] + [0.0] * (width - len(r)) for r in rows]
    text = "\n".join(",".join(repr(v) for v in r) for r in rows) + "\n"
    out = native.csv_parse(text.encode())
    assert out is not None, "plain numeric CSV must take the fast path"
    expect = np.array([[float(repr(v)) for v in r] for r in rows], np.float64)
    np.testing.assert_array_equal(out, expect)
