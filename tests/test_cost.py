"""Live cost attribution tests (telemetry/cost.py, ISSUE 19):

- `compiled_costs` / `classify` are the ONE implementation of the cost
  extraction + roofline arithmetic bench.py now shares.
- `ExecutableCostRegistry.capture` attributes every executable family —
  serve (batcher buckets, with pow2-padding-aware per-sample
  normalization), decode (step/prefill), train (the `timed_first_call`
  seam behind the process-default opt-in) — with zero ADDED recompiles
  (AOT lowering never touches jax's dispatch cache).
- Sampled dispatch histograms stay exact under concurrent dispatch, with
  zero sleeps.
- `/profile/cost` + `/profile/trace` HTTP contract on ServingServer and
  UIServer: 400 on bad params, bounded capture always stops.
- The deploy bytes-regression gauge + default alert rule: a
  quantized→f32 fallback deploy fires `deploy_bytes_regression`, a
  rollback resolves it.
- Donation failures are live metrics: a seeded unusable donation counts
  into `donation_warnings_total{site}`; the char-RNN TBPTT scan path
  (BENCH_r05's `float32[64,256]x4` suspect) stays at ZERO.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.decode import DecodeEngine
from deeplearning4j_tpu.serving import ModelRegistry, ServingServer
from deeplearning4j_tpu.telemetry.alerts import (AlertEngine, FIRING,
                                                 default_serving_rules)
from deeplearning4j_tpu.telemetry.cost import (MAX_TRACE_STEPS,
                                               ExecutableCostRegistry,
                                               abstractify, capture_trace,
                                               classify, compiled_costs,
                                               get_cost_registry,
                                               install_donation_watch,
                                               set_cost_registry)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.telemetry.trace import Tracer
from deeplearning4j_tpu.telemetry.xla import timed_first_call
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.zoo.models import transformer_lm


def _net(nin=6, nout=3, seed=0):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=nout, activation="softmax",
                               loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())
    return MultiLayerNetwork(conf).init()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read().decode())


def _get_status(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


class StubCompiled:
    """Duck-typed jax Compiled: fixed cost/memory analysis, so deploy-ratio
    and table logic test without paying real XLA compiles."""

    def __init__(self, flops, nbytes, temp=0.0):
        self._flops, self._nbytes, self._temp = flops, nbytes, temp

    def cost_analysis(self):
        return {"flops": self._flops, "bytes accessed": self._nbytes}

    def memory_analysis(self):
        class M:
            pass
        m = M()
        m.temp_size_in_bytes = self._temp
        m.argument_size_in_bytes = 0.0
        m.output_size_in_bytes = 0.0
        m.generated_code_size_in_bytes = 0.0
        return m


# ----------------------------------------------------- extraction helpers

def test_compiled_costs_of_real_executable_nonzero_and_flat_cache():
    """The AOT read bench.py + the live plane share: nonzero flops/bytes
    from a real compiled matmul, and lowering does NOT grow the jitted
    fn's dispatch cache (the zero-added-recompiles invariant)."""
    fn = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((8, 16), jnp.float32)
    fn(a, a.T)                                       # compile once
    before = fn._cache_size()
    comp = fn.lower(*abstractify((a, a.T))).compile()
    costs = compiled_costs(comp)
    assert costs["flops"] > 0 and costs["hbm_bytes"] > 0
    assert fn._cache_size() == before
    # degraded object: never raises, reports zeros
    assert compiled_costs(object())["flops"] == 0.0


def test_classify_matches_bench_roofline_arithmetic():
    flops, nbytes = 5.71e12, 85.07e9                 # BENCH_r05 headline
    tf_ceiling, bw = 174.9e12, 820e9
    cls = classify(flops, nbytes, tflops_ceiling=tf_ceiling,
                   hbm_bps_ceiling=bw, measured_ms=103.13)
    assert cls["roofline_compute_ms"] == pytest.approx(flops / tf_ceiling
                                                       * 1e3)
    assert cls["roofline_hbm_ms"] == pytest.approx(nbytes / bw * 1e3)
    assert cls["roofline_binding"] == "hbm"
    assert cls["roofline_util"] == pytest.approx(
        (nbytes / bw * 1e3) / 103.13)
    # flip the legs: tiny byte count on the same flops is matmul-bound
    assert classify(flops, 1.0, tflops_ceiling=tf_ceiling,
                    hbm_bps_ceiling=bw)["roofline_binding"] == "matmul"
    assert classify(1.0, 1.0)["roofline_util"] is None


def test_capture_normalizes_per_sample_and_labels_gauges():
    reg = MetricsRegistry()
    cost = ExecutableCostRegistry(reg)
    row = cost.capture_compiled("serve:b8", StubCompiled(800.0, 1600.0),
                                samples=8, version="v1")
    assert row["family"] == "serve"
    assert row["flops_per_sample"] == pytest.approx(100.0)
    assert row["hbm_bytes_per_sample"] == pytest.approx(200.0)
    assert reg.get("executable_flops_per_sample").get(
        executable="serve:b8") == pytest.approx(100.0)
    assert reg.get("roofline_binding").get(executable="serve:b8") in (0.0, 1.0)
    assert cost.to_dict()["executables"][0]["executable"] == "serve:b8"


def test_capture_error_counts_not_raises():
    reg = MetricsRegistry()
    cost = ExecutableCostRegistry(reg)
    assert cost.capture("bad", object(), (1, 2)) is None
    assert reg.get("cost_capture_errors_total").get(executable="bad") == 1


# ---------------------------------------------------------- train family

def test_train_family_captured_via_timed_first_call_opt_in():
    """The process-default registry is opt-in: with it set, the first call
    of a timed_first_call-wrapped train step lands a cost row; with it
    None (the unit-test default), nothing is captured."""
    reg = MetricsRegistry()
    cost = ExecutableCostRegistry(reg)
    assert get_cost_registry() is None
    set_cost_registry(cost)
    try:
        net = _net()
        x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        net.fit_batch(DataSet(x, y))
        labels = cost.labels()
        train = [l for l in labels if l.startswith("train_step")]
        assert train, labels
        row = cost.get(train[0])
        assert row["flops"] > 0 and row["hbm_bytes"] > 0
        # steady state: more steps, same executable, no new capture
        n = reg.get("cost_captures_total").get(executable=train[0],
                                               family="train_step")
        net.fit_batch(DataSet(x, y))
        assert reg.get("cost_captures_total").get(
            executable=train[0], family="train_step") == n
    finally:
        set_cost_registry(None)
    net2 = _net(seed=3)
    net2.fit_batch(DataSet(np.ones((2, 6), np.float32),
                           np.eye(3, dtype=np.float32)[[0, 1]]))
    assert cost.labels() == sorted(labels)      # nothing new after opt-out


# ---------------------------------------------------------- serve family

def test_serve_family_capture_normalizes_by_padded_bucket():
    """3 logical rows pad to the pow2 bucket of 4: the cost row's samples
    is the PADDED bucket (what the executable actually serves), so
    per-sample numbers divide by 4, and dispatches count."""
    registry = ModelRegistry()
    registry.register("v1", _net())
    registry.deploy("v1")
    server = ServingServer(None, registry=registry, max_latency_ms=1.0)
    server.batcher.start()
    try:
        x = np.random.default_rng(1).normal(size=(3, 6)).astype(np.float32)
        server.predict(x, wait_s=30)
        row = server.cost.get("serve:b4")
        assert row is not None, server.cost.labels()
        assert row["samples"] == 4
        assert row["flops"] > 0 and row["hbm_bytes"] > 0
        assert row["flops_per_sample"] == pytest.approx(row["flops"] / 4)
        assert row["version"] == "v1"
        assert row["dispatches"] >= 1
        # steady state: same bucket re-dispatches without re-capturing
        n = server.metrics.registry.get("cost_captures_total").get(
            executable="serve:b4", family="serve")
        server.predict(x, wait_s=30)
        assert server.metrics.registry.get("cost_captures_total").get(
            executable="serve:b4", family="serve") == n
        assert server.cost.dispatches("serve:b4") >= 2
    finally:
        server.stop()


# --------------------------------------------------------- decode family

def test_decode_family_capture_step_and_prefill():
    net = transformer_lm(vocab_size=24, d_model=32, n_layers=1, n_heads=2,
                         seed=1).init()
    reg = MetricsRegistry()
    cost = ExecutableCostRegistry(reg, sample_every=1)
    eng = DecodeEngine(net, slots=2, max_len=32, cost_registry=cost)
    eng.generate([1, 2, 3], 4)
    labels = cost.labels()
    assert "decode_step" in labels, labels
    assert any(l.startswith("decode_prefill") for l in labels), labels
    step = cost.get("decode_step")
    assert step["family"] == "decode"
    assert step["samples"] == 2                  # slots = tokens per dispatch
    assert step["flops"] > 0
    # sample_every=1 -> every dispatch sampled, util estimated live
    # (prefill yields the first token, so 4 new tokens = 3 step dispatches)
    assert step["dispatches"] >= 3
    assert cost.get("decode_step")["roofline_util"] is not None
    assert reg.get("dispatch_ms").count(executable="decode_step") >= 3


# --------------------------------------------------- dispatch sampling

def test_sampled_dispatch_histogram_exact_under_concurrency():
    """96 dispatches from 4 threads at sample_every=16: the dispatch count
    is exact and exactly ceil(96/16)=6 land in the histogram — one lock +
    int increment per unsampled dispatch, zero sleeps anywhere."""
    cost = ExecutableCostRegistry(MetricsRegistry(), sample_every=16)

    def worker():
        for _ in range(24):
            cost.record_dispatch("mesh_dispatch", 1.25)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert cost.dispatches("mesh_dispatch") == 96
    assert cost.dispatch_hist.count(executable="mesh_dispatch") == 6
    # sample_every=1 degenerates to every-dispatch observation
    every = ExecutableCostRegistry(MetricsRegistry(), sample_every=1)
    for _ in range(5):
        every.record_dispatch("d", 2.0)
    assert every.dispatch_hist.count(executable="d") == 5


# --------------------------------------------- deploy bytes regression

def test_deploy_bytes_regression_alert_fires_and_resolves():
    """A hot-swap that doubles hbm_bytes_per_sample (the f32-fallback
    shape) sets the ratio gauge past 1.2 and fires the default
    `deploy_bytes_regression` rule; rolling back re-captures the lean
    version and the rule resolves."""
    mreg = MetricsRegistry()
    cost = ExecutableCostRegistry(mreg)
    engine = AlertEngine(registry=mreg, rules=default_serving_rules(),
                         interval_s=3600.0)
    cost.capture_compiled("serve:b4", StubCompiled(100.0, 1000.0),
                          samples=4, version="v1")
    engine.evaluate()
    rule = next(r for r in engine.rules
                if r.name == "deploy_bytes_regression")
    assert rule.state != FIRING                 # no transition yet
    cost.capture_compiled("serve:b4", StubCompiled(100.0, 2000.0),
                          samples=4, version="v2")
    assert mreg.get("deploy_hbm_bytes_per_sample_ratio").get() \
        == pytest.approx(2.0)
    assert mreg.get("deploy_hbm_bytes_per_sample_ratio").get(
        family="serve") == pytest.approx(2.0)
    engine.evaluate()
    assert rule.state == FIRING, rule.status()
    # rollback: same label re-captured at the lean version's bytes
    cost.capture_compiled("serve:b4", StubCompiled(100.0, 1000.0),
                          samples=4, version="v1")
    assert mreg.get("deploy_hbm_bytes_per_sample_ratio").get() \
        == pytest.approx(0.5)
    engine.evaluate()
    assert rule.state != FIRING, rule.status()
    # a same-version re-capture (warmup replay) is NOT a deploy: ratio holds
    cost.capture_compiled("serve:b4", StubCompiled(100.0, 999.0),
                          samples=4, version="v1")
    assert mreg.get("deploy_hbm_bytes_per_sample_ratio").get() \
        == pytest.approx(0.5)


# ------------------------------------------------------- HTTP contract

def test_profile_cost_and_trace_http_contract_serving():
    server = ServingServer(_net(), port=0).start()
    try:
        x = np.ones((2, 6), np.float32)
        server.predict(x, wait_s=30)
        status, body = _get(server.url + "/profile/cost")
        assert status == 200
        assert body["ceilings"]["hbm_gbps_ceiling"] > 0
        rows = body["executables"]
        assert any(r["executable"].startswith("serve:") for r in rows)
        for r in rows:
            assert r["roofline_binding"] in ("hbm", "matmul")
        # unknown sort / family filters degrade, never 500
        assert _get_status(server.url + "/profile/cost?sort=bogus") == 200
        status, body = _get(server.url + "/profile/cost?family=nope")
        assert status == 200 and body["executables"] == []
        # trace: bad params are 400s, good one returns a bounded capture
        for bad in ("", "?steps=0", "?steps=-3", "?steps=abc",
                    f"?steps={MAX_TRACE_STEPS + 1}"):
            assert _get_status(server.url + "/profile/trace" + bad) == 400, bad
        server.predict(x, wait_s=30)
        status, body = _get(server.url + "/profile/trace?steps=2&timeout_s=0.2")
        assert status == 200
        assert body["otherData"]["requested_steps"] == 2
        assert body["otherData"]["captured_spans"] <= 2
    finally:
        server.stop()


def test_profile_routes_on_ui_server():
    cost = ExecutableCostRegistry(MetricsRegistry())
    cost.capture_compiled("serve:b2", StubCompiled(10.0, 20.0), samples=2)
    server = UIServer(port=0, cost=cost).start()
    try:
        status, body = _get(server.url + "/profile/cost")
        assert status == 200
        assert body["executables"][0]["executable"] == "serve:b2"
        assert _get_status(server.url + "/profile/trace?steps=0") == 400
    finally:
        server.stop()


def test_capture_trace_always_stops_when_idle():
    """The bounded capture returns even with zero traffic: the poll loop is
    iteration-capped, and the tracer's prior enabled state is restored."""
    tracer = Tracer(enabled=False)
    out = capture_trace(4, tracer=tracer, timeout_s=0.05, poll_s=0.01)
    assert out["otherData"]["captured_spans"] == 0
    assert tracer.enabled is False
    with pytest.raises(ValueError):
        capture_trace(0, tracer=tracer)
    with pytest.raises(ValueError):
        capture_trace(MAX_TRACE_STEPS + 1, tracer=tracer)


# ------------------------------------------------------- donation watch

def _unusable_donation():
    """Deterministic XLA 'donated buffers were not usable': every output is
    f16/smaller than the donated f32 input, so the donation can't stick."""
    fn = jax.jit(lambda x: jnp.float16(0) + x[:1].astype(jnp.float16),
                 donate_argnums=(0,))
    fn(jnp.ones((8,), jnp.float32))


def test_donation_watch_counts_with_site_label():
    reg = MetricsRegistry()
    uninstall = install_donation_watch(reg)
    try:
        _unusable_donation()
        series = reg.get("donation_warnings_total").series()
        counted = {k.get("site"): v for k, v in series if v > 0}
        assert counted, series
        assert any("test_cost.py" in site for site in counted), counted
    finally:
        uninstall()
    # after uninstall this subscriber's counter stays put
    before = reg.get("donation_warnings_total").get()
    _unusable_donation()
    assert reg.get("donation_warnings_total").get() == before


def test_char_rnn_tbptt_scan_has_zero_donation_warnings():
    """Regression pin for BENCH_r05's float32[64,256]x4 warning: the
    scanned TBPTT window path (the suspected carrier) compiles with every
    donation usable on this backend — the counter stays at ZERO through
    prepare/fit. If a carry change re-breaks donation, this counts it."""
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm
    reg = MetricsRegistry()
    uninstall = install_donation_watch(reg)
    try:
        net = char_rnn_lstm(vocab_size=12, hidden=8, layers=2, tbptt=4)
        net.init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 12, size=(4, 9))
        x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
        y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
        ds = DataSet(jnp.asarray(x), jnp.asarray(y))
        plan = net.prepare_steps([ds] * 2)
        assert plan is not None and plan[0] == "tbptt"
        net.fit_prepared(plan)
        assert reg.get("donation_warnings_total").get() == 0, \
            reg.get("donation_warnings_total").series()
    finally:
        uninstall()


# -------------------------------------------------------------- smoke tool

def test_smoke_profile_tool():
    """Fast variant of tools/smoke_profile.py: deploy, push traffic, scrape
    /profile/cost, and hold the full attribution contract — every active
    executable attributed with a roofline binding, zero steady-state
    recompiles/re-captures, and sampled-histogram overhead < 1% of
    steady-state dispatch time."""
    import tools.smoke_profile as smoke
    out = smoke.run(n_requests=12, concurrency=4)
    assert out["executables"] >= 1
    assert out["captures"] == out["executables"]
    assert out["dispatches"] > out["executables"]
    assert out["binding"] in ("hbm", "matmul")
    assert out["sampling_overhead_pct"] < 1.0


# ------------------------------------------------------------ fleet merge

def test_fleet_profile_merges_cost_tables_across_instances():
    """GET /fleet/profile: one live server with a warm cost table plus one
    dead peer — the merged view tags every row with its instance, sorts by
    bytes-per-sample, and reports the dead peer as an error entry instead
    of failing the merge."""
    from deeplearning4j_tpu.telemetry import FleetCollector
    server = ServingServer(_net(), max_batch_size=8,
                           max_latency_ms=1.0).start()
    try:
        x = np.random.default_rng(5).normal(size=(3, 6)).astype(np.float32)
        server.predict(x, wait_s=30)
        fc = FleetCollector([server.url, "http://127.0.0.1:9"],
                            names=["a", "dead"], interval_s=30.0,
                            timeout_s=2.0)
        assert fc.maybe_poll() is True
        p = fc.profile()
        assert set(p["instances"]) == {"a", "dead"}
        assert "error" in p["instances"]["dead"]
        assert p["instances"]["a"]["executables"], "live peer table empty"
        rows = p["executables"]
        assert rows and all(r["instance"] == "a" for r in rows)
        assert any(r["executable"].startswith("serve:") for r in rows)
        keys = [-float(r.get("hbm_bytes_per_sample") or 0.0) for r in rows]
        assert keys == sorted(keys), "rows not ranked by bytes/sample"
    finally:
        server.stop()
