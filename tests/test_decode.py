"""Autoregressive decode subsystem tests (decode/ + the flash decode path):

- flash_decode (the kernel's decode-mode path) matches the masked reference
  softmax, in and out of jit, Pallas-interpret and reference dispatch.
- greedy KV-cache decode == naive full-forward re-run, token-for-token AND
  to f32 tolerance on the probability rows, for transformer_lm (attention
  KV cache) and char_rnn_lstm (recurrent carry cache) — the ISSUE's
  acceptance parity.
- continuous batching: requests of varying prompt/output lengths join and
  leave the in-flight batch per token with the compile counters FLAT after
  warm-up, per-request outputs independent of co-batched neighbors.
- slot lifecycle: shedding, queued-deadline expiry, stop tokens, hot-swap
  (drain -> swap -> warm engine), DecodeUnsupported guards.
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.decode import (DecodeEngine, DecodeScheduler,
                                       DecodeUnsupported)
from deeplearning4j_tpu.kernels import flash_decode
from deeplearning4j_tpu.kernels.flash_attention import _decode_reference
from deeplearning4j_tpu.serving.admission import (DeadlineExceeded,
                                                  RejectedError)
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.telemetry.xla import CompileTracker
from deeplearning4j_tpu.zoo.models import char_rnn_lstm, transformer_lm

V = 24  # test vocab


def _tlm(seed=1, layers=1, causal=True, use_pallas=False):
    net = transformer_lm(vocab_size=V, d_model=32, n_layers=layers,
                         n_heads=2, seed=seed, causal=causal,
                         use_pallas=use_pallas)
    return net.init()


def _rnn(seed=2, layers=1):
    net = char_rnn_lstm(vocab_size=V, hidden=16, layers=layers, seed=seed)
    return net.init()


def _naive_greedy(net, prompt, n):
    """The oracle: re-run the FULL forward on the growing sequence each
    token (exactly what the KV cache exists to avoid). Returns (ids,
    last-position probability rows)."""
    ids = list(prompt)
    out, rows = [], []
    for _ in range(n):
        x = np.eye(V, dtype=np.float32)[np.asarray(ids)][None]
        y = np.asarray(net.output(x))
        rows.append(y[0, -1])
        out.append(int(y[0, -1].argmax()))
        ids.append(out[-1])
    return out, np.stack(rows)


def _engine_greedy(eng, cache, slot, prompt, n):
    """Greedy decode through the engine on one slot, collecting probs."""
    cache, nid, probs = eng.prefill(cache, slot, prompt)
    out, rows = [nid], [probs]
    ids = np.zeros((eng.slots,), np.int32)
    while len(out) < n:
        ids[slot] = out[-1]
        cache, nxt, p = eng.step(cache, ids)
        out.append(int(nxt[slot]))
        rows.append(p[slot])
    return cache, out, np.stack(rows)


# ------------------------------------------------------------- flash decode

@pytest.mark.parametrize("use_pallas", [False, True])
def test_flash_decode_matches_reference(use_pallas):
    rng = np.random.default_rng(0)
    S, C, H, D = 3, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(S, 1, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(S, C, H, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(S, C, H, D)).astype(np.float32))
    lens = jnp.asarray([5, 16, 1], jnp.int32)
    ref = _decode_reference(q, k, v, lens, 1.0 / np.sqrt(D))
    out = flash_decode(q, k, v, lens, use_pallas=use_pallas)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    jit_out = jax.jit(lambda *a: flash_decode(*a, use_pallas=use_pallas))(
        q, k, v, lens)
    np.testing.assert_allclose(np.asarray(jit_out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_only_valid_positions_matter():
    """Entries past the per-slot length must not influence the output —
    the masking contract continuous batching relies on."""
    rng = np.random.default_rng(1)
    S, C, H, D = 2, 8, 1, 8
    q = jnp.asarray(rng.normal(size=(S, 1, H, D)).astype(np.float32))
    k = rng.normal(size=(S, C, H, D)).astype(np.float32)
    v = rng.normal(size=(S, C, H, D)).astype(np.float32)
    lens = jnp.asarray([3, 6], jnp.int32)
    a = flash_decode(q, jnp.asarray(k), jnp.asarray(v), lens)
    k2, v2 = k.copy(), v.copy()
    k2[0, 3:] = 99.0    # garbage beyond each slot's length
    v2[1, 6:] = -99.0
    b = flash_decode(q, jnp.asarray(k2), jnp.asarray(v2), lens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# ------------------------------------------------------------ greedy parity

@pytest.mark.parametrize("make,label", [(_tlm, "transformer_lm"),
                                        (_rnn, "char_rnn_lstm")])
def test_greedy_parity_kv_cache_vs_full_forward(make, label):
    """ISSUE acceptance: KV-cache incremental decode == naive full-forward
    re-run, token-for-token under greedy sampling, probs to f32 tolerance."""
    net = make(layers=2)
    prompt = [3, 1, 4, 15, 9]
    want, want_rows = _naive_greedy(net, prompt, 8)
    eng = DecodeEngine(net, slots=2, max_len=64)
    _, got, got_rows = _engine_greedy(eng, eng.init_cache(), 1, prompt, 8)
    assert got == want, label
    np.testing.assert_allclose(got_rows, want_rows, rtol=1e-4, atol=1e-5,
                               err_msg=label)


def test_greedy_parity_with_pallas_decode_path():
    """use_pallas=True routes the decode step through the Pallas kernel
    (interpret mode on CPU) and prefill through the masked flash kernel."""
    net = _tlm(seed=5, use_pallas=True)
    prompt = [2, 7, 7, 1]
    want, _ = _naive_greedy(net, prompt, 6)
    got = DecodeEngine(net, slots=1, max_len=32).generate(prompt, 6)
    assert got == want


def test_network_generate_api_both_types():
    for net in (_tlm(seed=3), _rnn(seed=4)):
        want, _ = _naive_greedy(net, [5, 2, 9], 5)
        assert net.generate([5, 2, 9], 5) == want
        # engine is cached on the model: a second call mints no new engine
        eng = net._decode_engine
        assert net.generate([5, 2, 9], 5) == want
        assert net._decode_engine is eng


def test_generate_stop_id_and_capacity():
    net = _tlm(seed=6)
    full = net.generate([1, 2, 3], 8)
    stop = full[2]
    stopped = net.generate([1, 2, 3], 8, stop_id=stop)
    # greedy decode is deterministic, so the stop cuts at the token's FIRST
    # occurrence (inclusive)
    assert stopped == full[:full.index(stop) + 1]


def test_decode_unsupported_models():
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (GravesBidirectionalLSTM,
                                                   RnnOutputLayer)
    from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(GravesBidirectionalLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="MCXENT"))
            .input_type(InputType.recurrent(V)).build())
    with pytest.raises(DecodeUnsupported):
        DecodeEngine(MultiLayerNetwork(conf).init(), slots=1, max_len=32)
    with pytest.raises(DecodeUnsupported):
        DecodeEngine(_tlm(seed=7, causal=False), slots=1, max_len=32)


# ----------------------------------------------------- continuous batching

def _scheduler(net, version="v1", slots=3, max_len=64, **kw):
    registry = ModelRegistry()
    registry.register(version, net)
    registry.deploy(version)
    mreg = MetricsRegistry()
    sched = DecodeScheduler(registry, mreg, slots=slots, max_len=max_len,
                            compile_tracker=CompileTracker(mreg), **kw)
    return sched, registry, mreg


def test_continuous_batching_join_leave_compile_flat():
    """ISSUE acceptance: with requests of varying prompt/output lengths
    joining and leaving mid-flight, the decode compile counters are FLAT
    after warm-up, and per-request outputs are independent of co-batched
    neighbors (== the isolated single-request run)."""
    net = _tlm(seed=8, layers=2)
    sched, _, mreg = _scheduler(net, slots=3)
    sched.start()
    try:
        shapes = [([3, 1, 4], 6), ([5, 2], 4), ([7, 7, 7, 7, 2, 1], 8),
                  ([1], 3), ([9, 8, 7, 6], 5)]
        solo = {i: net.generate(p, n) for i, (p, n) in enumerate(shapes)}
        # warm-up round: every prompt bucket + the step compile here
        warm = [sched.submit(p, max_new_tokens=n) for p, n in shapes]
        for f in warm:
            f.result(timeout=120)
        compiles = mreg.get("compiles_total").get()
        jit_compiles = mreg.get("jit_compiles_total")
        jit_before = jit_compiles.get() if jit_compiles is not None else 0
        # steady state: same length mix, staggered arrivals -> requests
        # join slots as earlier ones retire, per token
        futs = {}
        for i, (p, n) in enumerate(shapes):
            futs[i] = sched.submit(p, max_new_tokens=n)
            time.sleep(0.01)
        results = {i: f.result(timeout=120) for i, f in futs.items()}
        for i, (p, n) in enumerate(shapes):
            assert results[i]["tokens"] == solo[i], \
                f"request {i} disturbed by co-batched neighbors"
            assert results[i]["finish_reason"] in ("length", "capacity")
        assert mreg.get("compiles_total").get() == compiles, \
            "steady-state decode recompiled"
        if jit_compiles is not None:
            assert jit_compiles.get() == jit_before
        # the hard assertion: each decode executable traced exactly once
        counts = sched._engine.executable_counts()
        assert counts and all(v == 1 for v in counts.values()), counts
        # telemetry populated: TTFT + ITL saw every request/token
        assert mreg.get("decode_requests_total").get() == 2 * len(shapes)
        assert mreg.get("decode_ttft_ms").percentiles()["p50"] is not None
        assert mreg.get("decode_itl_ms").percentiles()["p50"] is not None
    finally:
        sched.stop()


def test_scheduler_shed_expiry_and_stop_token():
    net = _tlm(seed=9)
    sched, _, mreg = _scheduler(net, slots=1, queue_capacity=2)
    # not started: the queue only fills
    sched.submit([1, 2], max_new_tokens=2)
    sched.submit([1, 2], max_new_tokens=2)
    with pytest.raises(RejectedError):
        sched.submit([1, 2], max_new_tokens=2)
    assert mreg.get("decode_shed_total").get() == 1
    # an already-expired deadline fails at admission with DeadlineExceeded
    sched._queue.clear()
    f = sched.submit([3, 1, 4], max_new_tokens=4, timeout_ms=0.0)
    sched.start()
    try:
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=60)
        assert mreg.get("decode_expired_total").get() == 1
        # stop token retires a slot early, mid-batch
        full = net.generate([3, 1, 4], 6)
        res = sched.generate([3, 1, 4], max_new_tokens=6, stop_id=full[1])
        assert res["tokens"] == full[:2] and res["finish_reason"] == "stop"
        # unservable size: a clear client error, not a shed
        with pytest.raises(ValueError):
            sched.submit(list(range(10)), max_new_tokens=1000)
    finally:
        sched.stop()


def test_hot_swap_drains_then_swaps_and_warm_engine_stays_warm():
    net1, net2 = _tlm(seed=10), _tlm(seed=11)
    sched, registry, mreg = _scheduler(net1, slots=2)
    sched.start()
    try:
        r1 = sched.generate([4, 4, 1], max_new_tokens=4)
        assert r1["version"] == "v1"
        assert r1["tokens"] == net1.generate([4, 4, 1], 4)
        # deploy v2 with the scheduler's warm-up (what ServingServer.deploy
        # wires): step + observed buckets compile BEFORE the swap
        registry.register("v2", net2)
        registry.deploy("v2", warmup=sched.warmup)
        compiles = mreg.get("compiles_total").get()
        r2 = sched.generate([4, 4, 1], max_new_tokens=4)
        assert r2["version"] == "v2"
        assert r2["tokens"] == net2.generate([4, 4, 1], 4)
        assert mreg.get("compiles_total").get() == compiles, \
            "post-warm-up swap recompiled"
        # rollback: the v1 engine is cached -> no recompile either
        registry.rollback(warmup=sched.warmup)
        compiles = mreg.get("compiles_total").get()
        r3 = sched.generate([4, 4, 1], max_new_tokens=4)
        assert r3["version"] == "v1" and r3["tokens"] == r1["tokens"]
        assert mreg.get("compiles_total").get() == compiles
    finally:
        sched.stop()


def test_scheduler_survives_engine_error_and_serves_next():
    net = _tlm(seed=12)
    sched, _, mreg = _scheduler(net, slots=2, max_len=64)
    sched.start()
    try:
        ok = sched.generate([1, 2, 3], max_new_tokens=3)
        assert len(ok["tokens"]) == 3
        # sabotage one wave: an engine whose prefill raises
        class Boom(Exception):
            pass

        orig = sched._engine.prefill

        def boom(*a, **k):
            sched._engine.prefill = orig
            raise Boom("injected")
        sched._engine.prefill = boom
        with pytest.raises(Boom):
            sched.generate([1, 2], max_new_tokens=2)
        assert mreg.get("decode_errors_total").get() >= 1
        # the loop survived and the next request serves fine
        again = sched.generate([1, 2, 3], max_new_tokens=3)
        assert again["tokens"] == ok["tokens"]
    finally:
        sched.stop()


# ------------------------------------------------------------- smoke tool

def test_smoke_decode_tool():
    """End-to-end /generate smoke (deploy zip -> concurrent staggered
    streams -> zero steady-state recompiles, zero donation warnings, TTFT
    populated) — fast variant of tools/smoke_decode.py, mirroring the
    smoke_serving/smoke_ingest wiring."""
    import tools.smoke_decode as smoke
    out = smoke.run(n_requests=6, max_new_tokens=4)
    assert out["steady_state_compiles"] == 0
    assert out["donation_warnings"] == 0
    assert out["ttft_ms_p50"] is not None
    assert out["parity_ok"]


def test_generate_routed_through_fleet_frontend_with_failover():
    """/generate rides the same failover/breaker path as /predict: a dead
    replica's requests fail over transparently, zero client errors."""
    from deeplearning4j_tpu.serving import FleetFrontend, ServingServer
    from deeplearning4j_tpu.util.http import post_json
    net = _tlm(seed=20)
    solo = net.generate([6, 3], 4)
    s1 = ServingServer(net, decode=True, decode_slots=2, decode_max_len=64,
                       alert_interval_s=0).start()
    s2 = ServingServer(net, decode=True, decode_slots=2, decode_max_len=64,
                       alert_interval_s=0).start()
    fe = FleetFrontend([s1.url, s2.url], names=["a", "b"],
                       health_interval_s=1e9, alert_interval_s=0).start()
    try:
        res = post_json(fe.url + "/generate",
                        {"prompt": [6, 3], "max_new_tokens": 4}, timeout=120)
        assert res["tokens"] == solo and res["replica"] in ("a", "b")
        # kill one replica: the next generates all land on the survivor
        s1.stop()
        survivors = set()
        for _ in range(3):
            res = post_json(fe.url + "/generate",
                            {"prompt": [6, 3], "max_new_tokens": 4},
                            timeout=120)
            assert res["tokens"] == solo
            survivors.add(res["replica"])
        assert survivors == {"b"}
    finally:
        fe.stop()
        s2.stop()
        try:
            s1.stop()
        except Exception:
            pass


def test_unsupported_deployed_model_fails_fast_without_spinning():
    """A deployed model with no decode semantics must fail /generate
    requests promptly (DecodeUnsupported) — not leave them queued forever
    while the loop spins on an engine that can never build."""
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (GravesBidirectionalLSTM,
                                                   RnnOutputLayer)
    from deeplearning4j_tpu.nn.multilayer.network import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(GravesBidirectionalLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="MCXENT"))
            .input_type(InputType.recurrent(V)).build())
    sched, _, mreg = _scheduler(MultiLayerNetwork(conf).init(), slots=1)
    sched.start()
    try:
        with pytest.raises(DecodeUnsupported):
            sched.generate([1, 2], max_new_tokens=2, wait_s=30)
        assert sched.depth() == 0                  # nothing left spinning
        assert mreg.get("decode_errors_total").get() >= 1
        assert sched._thread.is_alive()
    finally:
        sched.stop()


def test_abandon_withdraws_queued_and_clamps_active():
    net = _tlm(seed=13)
    sched, _, _ = _scheduler(net, slots=1)
    # not started: the submit stays queued -> abandon withdraws + fails it
    fut = sched.submit([1, 2], max_new_tokens=4)
    assert sched.abandon(fut) and sched.depth() == 0
    with pytest.raises(RejectedError):
        fut.result(timeout=1)
    # unknown future: no-op
    from concurrent.futures import Future
    assert not sched.abandon(Future())
