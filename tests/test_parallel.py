"""Parallelism tests on the 8-device virtual CPU mesh (conftest forces
xla_force_host_platform_device_count=8), mirroring the reference's approach of
testing distributed semantics in-process (ParallelWrapperTest.java,
BaseSparkTest.java with master=local[n]).
"""
import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                INDArrayDataSetIterator, Adam, Sgd)
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)
from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper


def _toy(n=256, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    y = np.argmax(X @ w, axis=1)
    return X, np.eye(nout, dtype=np.float32)[y]


def _conf(nin=8, nout=3, updater=None, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_trainer_matches_single_device():
    """DP allreduce-of-gradients must equal the single-device step on the same
    global batch (the correctness contract replacing the reference's
    averaging-equivalence tests)."""
    X, Y = _toy(n=64)
    net_a = MultiLayerNetwork(_conf()).init()
    net_b = MultiLayerNetwork(_conf()).init()
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params())

    ds = DataSet(X, Y)
    net_a.fit_batch(ds)

    trainer = ShardedTrainer(net_b, mesh=make_mesh(n_data=8))
    trainer.fit_batch(ds)
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_sharded_trainer_trains():
    X, Y = _toy(n=256)
    net = MultiLayerNetwork(_conf(updater=Adam(1e-2))).init()
    trainer = ShardedTrainer(net, mesh=make_mesh(n_data=8))
    s0 = net.score(DataSet(X, Y))
    for _ in range(30):
        trainer.fit_batch(DataSet(X, Y))
    assert net.score(DataSet(X, Y)) < s0 * 0.6


def test_parallel_wrapper_facade():
    X, Y = _toy(n=256)
    net = MultiLayerNetwork(_conf(updater=Adam(1e-2))).init()
    pw = (ParallelWrapper.builder(net)
          .workers(8).prefetch_buffer(2).averaging_frequency(1)
          .build())
    s0 = net.score(DataSet(X, Y))
    pw.fit(INDArrayDataSetIterator(X, Y, 64), epochs=10)
    assert net.score(DataSet(X, Y)) < s0


def test_tensor_parallel_dense():
    """TP (new capability): kernel sharded over the model axis; results match
    replicated execution."""
    X, Y = _toy(n=32)
    net_a = MultiLayerNetwork(_conf(seed=7)).init()
    net_b = MultiLayerNetwork(_conf(seed=7)).init()
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    trainer = ShardedTrainer(net_b, mesh=mesh, rules=rules)
    ds = DataSet(X, Y)
    net_a.fit_batch(ds)
    trainer.fit_batch(ds)
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
