"""Parallelism tests on the 8-device virtual CPU mesh (conftest forces
xla_force_host_platform_device_count=8), mirroring the reference's approach of
testing distributed semantics in-process (ParallelWrapperTest.java,
BaseSparkTest.java with master=local[n]).
"""
import os
import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                INDArrayDataSetIterator, Adam, Sgd)
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)
from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper


def _toy(n=256, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    y = np.argmax(X @ w, axis=1)
    return X, np.eye(nout, dtype=np.float32)[y]


def _conf(nin=8, nout=3, updater=None, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_trainer_matches_single_device():
    """DP allreduce-of-gradients must equal the single-device step on the same
    global batch (the correctness contract replacing the reference's
    averaging-equivalence tests)."""
    X, Y = _toy(n=64)
    net_a = MultiLayerNetwork(_conf()).init()
    net_b = MultiLayerNetwork(_conf()).init()
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params())

    ds = DataSet(X, Y)
    net_a.fit_batch(ds)

    trainer = ShardedTrainer(net_b, mesh=make_mesh(n_data=8))
    trainer.fit_batch(ds)
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_sharded_trainer_trains():
    X, Y = _toy(n=256)
    net = MultiLayerNetwork(_conf(updater=Adam(1e-2))).init()
    trainer = ShardedTrainer(net, mesh=make_mesh(n_data=8))
    s0 = net.score(DataSet(X, Y))
    for _ in range(30):
        trainer.fit_batch(DataSet(X, Y))
    assert net.score(DataSet(X, Y)) < s0 * 0.6


def test_parallel_wrapper_facade():
    X, Y = _toy(n=256)
    net = MultiLayerNetwork(_conf(updater=Adam(1e-2))).init()
    pw = (ParallelWrapper.builder(net)
          .workers(8).prefetch_buffer(2).averaging_frequency(1)
          .build())
    s0 = net.score(DataSet(X, Y))
    pw.fit(INDArrayDataSetIterator(X, Y, 64), epochs=10)
    assert net.score(DataSet(X, Y)) < s0


def test_tensor_parallel_dense():
    """TP (new capability): kernel sharded over the model axis; results match
    replicated execution."""
    X, Y = _toy(n=32)
    net_a = MultiLayerNetwork(_conf(seed=7)).init()
    net_b = MultiLayerNetwork(_conf(seed=7)).init()
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    trainer = ShardedTrainer(net_b, mesh=mesh, rules=rules)
    ds = DataSet(X, Y)
    net_a.fit_batch(ds)
    trainer.fit_batch(ds)
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_opt_state_inherits_param_shardings():
    """Momentum/adam moments must carry the SAME sharding as their params —
    a replicated opt state forces GSPMD resharding every step (VERDICT r2
    weak #5)."""
    from jax.sharding import PartitionSpec as P
    net = MultiLayerNetwork(_conf(updater=Adam(1e-2))).init()
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)

    from deeplearning4j_tpu.parallel.sharding import _param_paths
    pshard = {p: l.sharding for p, l in _param_paths(net.params).items()}
    leaves = jax.tree_util.tree_flatten_with_path(net.opt_state)[0]
    checked = 0
    for path, leaf in leaves:
        if not hasattr(leaf, "sharding"):
            continue
        pstr = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
                        for k in path)
        for ppath, s in pshard.items():
            layer, _, tail = ppath.partition("/")
            if (pstr.startswith(layer + "/") and pstr.endswith("/" + tail)
                    and leaf.shape == np.shape(net.params[layer][tail])):
                assert leaf.sharding.spec == s.spec, (pstr, leaf.sharding, s)
                checked += 1
    assert checked >= 4  # both layers' W and b moments found and verified

    # and the step must still be correct
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))


def test_partial_batch_pads_and_masks_no_example_dropped():
    """A batch not divisible by the data axis trains on ALL examples: the
    padded rows are loss-masked, so the sharded gradient equals the
    single-device gradient over the same (full) batch (VERDICT r2 weak #6)."""
    X, Y = _toy(n=27)  # 27 % 8 != 0; old behavior dropped 3 examples
    net_a = MultiLayerNetwork(_conf()).init()
    net_b = MultiLayerNetwork(_conf()).init()
    net_a.fit_batch(DataSet(X, Y))
    trainer = ShardedTrainer(net_b, mesh=make_mesh(n_data=8))
    trainer.fit_batch(DataSet(X, Y))
    np.testing.assert_allclose(net_a.get_flat_params(), net_b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    assert net_b.examples_fit == 27

    # even a batch SMALLER than the data axis now trains (was: skipped)
    net_c = MultiLayerNetwork(_conf()).init()
    net_d = MultiLayerNetwork(_conf()).init()
    net_c.fit_batch(DataSet(X[:5], Y[:5]))
    t2 = ShardedTrainer(net_d, mesh=make_mesh(n_data=8))
    t2.fit_batch(DataSet(X[:5], Y[:5]))
    np.testing.assert_allclose(net_c.get_flat_params(), net_d.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_sharded_trainer_computation_graph():
    """ShardedTrainer over a ComputationGraph (the CG step arity was never
    exercised before)."""
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration as NNC
    gb = (NNC.builder().seed(5).updater(Sgd(0.1)).graph_builder()
          .add_inputs("in"))
    gb.add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="MCXENT"), "d1")
    gb.set_outputs("out")
    gb.set_input_types(InputType.feed_forward(8))
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
    net = ComputationGraph(gb.build()).init()
    X, Y = _toy(n=64)
    trainer = ShardedTrainer(net, mesh=make_mesh(n_data=8))
    s0 = net.score(DataSet(X, Y))
    for _ in range(20):
        trainer.fit_batch(DataSet(X, Y))
    assert net.score(DataSet(X, Y)) < s0 * 0.8


def test_binomial_preprocessor_uses_step_rng():
    """Identical batches must get DIFFERENT Bernoulli noise across steps now
    that the step rng is threaded through the preprocessor SPI (VERDICT r2
    weak #7)."""
    from deeplearning4j_tpu.nn.conf.preprocessors import BinomialSamplingPreProcessor
    pre = BinomialSamplingPreProcessor(seed=3)
    x = np.full((4, 6), 0.5, np.float32)
    a = np.asarray(pre(x, rng=jax.random.PRNGKey(1)))
    b = np.asarray(pre(x, rng=jax.random.PRNGKey(2)))
    assert not np.array_equal(a, b)
    # and deterministic for the same key
    c = np.asarray(pre(x, rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(a, c)


def test_multihost_helpers_single_process():
    """Single-process semantics of the multi-host bootstrap helpers (the
    multi-process path uses the same jax.distributed machinery; here
    process_count()==1)."""
    from deeplearning4j_tpu.parallel import multihost
    from jax.sharding import PartitionSpec as P
    multihost.initialize()  # no coordinator: single-process no-op
    assert multihost.process_count() == 1
    assert multihost.process_index() == 0
    assert multihost.local_device_count() == 8
    mesh = multihost.global_mesh(n_model=2)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    # batch slicing covers the global batch exactly, no overlap
    s, e = multihost.process_batch_slice(37)
    assert (s, e) == (0, 37)

    # host-local -> global assembly round-trips
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    (gx,) = multihost.host_local_to_global([x], mesh, [P("data", None)])
    np.testing.assert_array_equal(np.asarray(gx), x)

    # and a sharded train step runs over the assembled global batch
    net = MultiLayerNetwork(_conf()).init()
    trainer = ShardedTrainer(net, mesh=make_mesh(n_data=8))
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    assert np.isfinite(net.score_value)


def test_pipeline_parallel_matches_single_device():
    """GPipe pipeline over 2 stages x 4 microbatches must produce the SAME
    update as single-device full-batch training (mean losses => microbatch
    gradient averaging is exact)."""
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer

    def build(seed=21):
        conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
                .input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf).init()

    X, Y = _toy(n=32)
    a, b = build(), build()
    a.fit_batch(DataSet(X, Y))

    pt = PipelineTrainer(b, n_stages=2, n_microbatches=4,
                         devices=jax.devices()[:2])
    score = pt.fit_batch(DataSet(X, Y))
    assert np.isfinite(score)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    # stage params actually live on their stage devices
    d0 = list(b.params["0"].values())[0].devices()
    d3 = list(b.params["3"].values())[0].devices()
    assert d0 != d3, "stages share a device; no pipeline placement happened"

    # multiple steps keep training (loss decreases)
    s0 = b.score_value
    for _ in range(10):
        pt.fit_batch(DataSet(X, Y))
    assert b.score_value < s0


def test_pipeline_parallel_four_stages_adam():
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    ref = MultiLayerNetwork(conf).init()  # same conf object; params re-init
    X, Y = _toy(n=64)
    pt = PipelineTrainer(net, n_stages=4, n_microbatches=8)
    ref.fit_batch(DataSet(X, Y))
    pt.fit_batch(DataSet(X, Y))
    np.testing.assert_allclose(ref.get_flat_params(), net.get_flat_params(),
                               rtol=1e-4, atol=1e-6)


def test_pipeline_three_stages_four_layers_no_empty_stage():
    """Regression: uneven layer counts must never yield an empty stage
    (ceil-split gave [0,2,4,4] for 4 layers / 3 stages)."""
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    pt = PipelineTrainer(net, n_stages=3, n_microbatches=2,
                         devices=jax.devices()[:3])
    X, Y = _toy(n=8)
    assert np.isfinite(pt.fit_batch(DataSet(X, Y)))
    with pytest.raises(ValueError, match="stages > "):
        PipelineTrainer(MultiLayerNetwork(conf).init(), n_stages=5)


def test_pipeline_updates_bn_running_stats_per_microbatch():
    """Stateful layers thread through the compiled stages: BatchNorm running
    stats after one pipelined step must equal M sequential microbatch EMA
    updates (the per-microbatch semantics every 1F1B implementation has).
    BN is placed FIRST so the oracle depends only on the raw inputs."""
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
    from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
    conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.1)).list()
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    M = 4
    pt = PipelineTrainer(net, n_stages=2, n_microbatches=M,
                         devices=jax.devices()[:2])
    X, Y = _toy(n=32)
    assert np.isfinite(pt.fit_batch(DataSet(X, Y)))

    decay = 0.9
    mean, var = np.zeros(8), np.ones(8)  # BN state init
    for xm in np.split(X, M):
        mu = xm.mean(axis=0)
        mean = decay * mean + (1 - decay) * mu
        var = decay * var + (1 - decay) * ((xm - mu) ** 2).mean(axis=0)
    np.testing.assert_allclose(np.asarray(net.states["0"]["mean"]), mean,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(net.states["0"]["var"]), var,
                               rtol=1e-5, atol=1e-6)
    # and training continues to make progress with BN in the pipeline
    s0 = float(net.score_value)
    for _ in range(10):
        pt.fit_batch(DataSet(X, Y))
    assert float(net.score_value) < s0


@pytest.mark.skipif(not os.environ.get("DL4J_TPU_SOAK"),
                    reason="wall-clock perf property; flaky on loaded CI "
                           "cores — set DL4J_TPU_SOAK=1 to run (the "
                           "rig-independent schedule property is covered by "
                           "test_pipeline_schedule_achieves_1f1b_bubble)")
def test_pipeline_async_schedule_overlaps_stages():
    """The 1F1B schedule's value is that the host only ENQUEUES compiled
    stage executables and async dispatch overlaps them across stage devices.
    Measured form: the pipelined step must be faster than the IDENTICAL
    executables with a host fence after every enqueue (which reduces the
    schedule to serialized stage-at-a-time execution). On real multi-chip
    hardware this same property is what turns into linear pipeline speedup;
    the virtual-device CPU mesh still shows it because XLA executables from
    different devices interleave."""
    import time
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer

    def build():
        b = NeuralNetConfiguration.builder().seed(11).updater(Sgd(0.05)).list()
        for _ in range(8):
            b = b.layer(DenseLayer(n_out=512, activation="tanh"))
        conf = (b.layer(OutputLayer(n_out=8, activation="softmax",
                                    loss="MCXENT"))
                .input_type(InputType.feed_forward(512))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 512)).astype(np.float32)
    Y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 256)]
    ds = DataSet(X, Y)
    pt = PipelineTrainer(build(), n_stages=4, n_microbatches=8,
                         devices=jax.devices()[:4])

    def wall(fenced, reps=3):
        pt._fence_every_op = fenced
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            pt.fit_batch(ds)
            jax.block_until_ready(pt.model.params)
            best = min(best, time.perf_counter() - t0)
        return best

    wall(False)  # compile both paths
    wall(True)
    # one shared physical core bounds the measurable gain (observed ~0.83
    # fenced-relative); a loaded CI core can jitter past that, so the
    # property gets three chances before the test calls it a failure
    ratios = []
    for _ in range(3):
        overlapped = wall(False)
        fenced = wall(True)
        ratios.append(overlapped / fenced)
        if ratios[-1] < 0.95:
            break
    pt._fence_every_op = False
    assert min(ratios) < 0.95, (
        f"pipelined/fenced wall ratios {ratios} never under 0.95 — stage "
        f"execution is not overlapping")


def test_pipeline_gather_enables_inference_and_training_resumes():
    """Stage params live on different devices during pipeline training, so
    the model's own jitted output() fails placement checks; gather() brings
    everything to one device for inference/serialization, and the next
    fit_batch transparently re-places the stages."""
    from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
    from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
    conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    pt = PipelineTrainer(net, n_stages=2, n_microbatches=4,
                         devices=jax.devices()[:2])
    X, Y = _toy(n=32)
    pt.fit_batch(DataSet(X, Y))
    with pytest.raises(ValueError, match="devices"):
        net.output(X)                   # split placement: must be explicit
    pt.gather()
    out = np.asarray(net.output(X))     # inference uses the running stats
    assert out.shape == (32, 3) and np.isfinite(out).all()
    s = pt.fit_batch(DataSet(X, Y))     # training resumes (re-placement)
    assert np.isfinite(s)
    d0 = list(net.params["0"].values())[0].devices()
    d3 = list(net.params["3"].values())[0].devices()
    assert d0 != d3, "stages were not re-placed after gather()"


def test_pipeline_schedule_achieves_1f1b_bubble():
    """VERDICT r4 next #6: rig-independent proof the enqueued schedule IS
    1F1B. profile_schedule records per-op durations (fenced) and
    simulate_1f1b replays the enqueue order under its dataflow deps; with
    uniform synthetic durations (fwd = bwd = 1, fused last = 2) the replay
    must hit EXACTLY the ideal bubble (S-1)/(M+S-1), and per-stage busy
    time must be 2M units — the wall clock of the shared-core CPU mesh
    never enters."""
    from deeplearning4j_tpu.parallel.pipeline import (PipelineTrainer,
                                                      simulate_1f1b)
    S, M = 4, 8
    conf_b = NeuralNetConfiguration.builder().seed(11).updater(Sgd(0.05)).list()
    for _ in range(S):
        conf_b = conf_b.layer(DenseLayer(n_out=32, activation="tanh"))
    conf = (conf_b.layer(OutputLayer(n_out=3, activation="softmax",
                                     loss="MCXENT"))
            .input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    pt = PipelineTrainer(net, n_stages=S, n_microbatches=M,
                         devices=jax.devices()[:S])
    X, Y = _toy(n=M * 4, nin=16)
    pt.fit_batch(DataSet(X, Y))   # compile everything outside the profile
    prof = pt.profile_schedule(DataSet(X, Y))
    assert len(prof["op_log"]) == 2 * M * S - M  # M*S fwd(+fused last) + M*(S-1) bwd

    # replace measured durations with the uniform-cost model: the schedule's
    # intrinsic bubble must equal the 1F1B ideal exactly
    uniform = [(kind, mb, s, 2.0 if kind == "last" else 1.0)
               for kind, mb, s, _ in prof["op_log"]]
    sim = simulate_1f1b(uniform, S, M)
    ideal = (S - 1) / (M + S - 1)
    assert sim["ideal_bubble"] == ideal
    assert all(abs(b - 2 * M) < 1e-9 for b in sim["per_stage_busy"])
    # makespan of ideal 1F1B with unit fwd/bwd: 2*(M + S - 1) slots
    assert abs(sim["makespan"] - 2 * (M + S - 1)) < 1e-9
    assert abs(sim["bubble_fraction"] - ideal) < 1e-9

    # with MEASURED durations the stages aren't perfectly balanced (the
    # fused last op runs ~2x a mid-stage fwd) and the fenced wall-clock
    # durations themselves carry shared-core scheduler jitter, so exact
    # ideal isn't reachable — but the schedule must recover a solid
    # majority of the parallelism a serial stage-at-a-time execution
    # wastes (serial bubble is 1 - 1/S; typical measured ~0.36-0.43 vs
    # 0.75 serial). Generous margin so CI load can't flake it; the EXACT
    # assertions above carry the rig-independent claim.
    serial_bubble = 1.0 - 1.0 / S
    assert prof["bubble_fraction"] < 0.8 * serial_bubble, (
        prof["bubble_fraction"], serial_bubble)
