"""Mixed-precision (compute_dtype=bfloat16) tests: f32 master params, BN
statistics, and loss, with bf16 MXU-bound compute (SURVEY.md §4.1 tolerance
tiers; the reference's analog is the fp16 cuDNN bypass ConvolutionLayer.java:158)."""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, ConvolutionLayer,
                                SubsamplingLayer, DenseLayer, OutputLayer,
                                MultiLayerNetwork, DataSet, Adam, BatchNormalization)


def _net(compute_dtype):
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .compute_dtype(compute_dtype).list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8, activation="relu",
                                    convolution_mode="same"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(8, 8, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8, 8, 1)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return x, y


def test_bf16_training_converges_with_f32_master_state():
    x, y = _data()
    net = _net("bfloat16")
    s0 = net.score(x, y)
    for _ in range(20):
        net.fit_batch(DataSet(x, y))
    assert net.score_value < 0.5 * s0
    # master params / opt state / BN stats stay f32
    for tree in (net.params, net.states, net.opt_state):
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "dtype") and np.issubdtype(leaf.dtype, np.floating):
                assert leaf.dtype == np.float32, leaf.dtype


def test_bf16_matches_f32_within_tolerance():
    x, y = _data()
    n32, n16 = _net(None), _net("bfloat16")
    for _ in range(10):
        n32.fit_batch(DataSet(x, y))
        n16.fit_batch(DataSet(x, y))
    o32 = np.asarray(n32.output(x))
    o16 = np.asarray(n16.output(x))
    assert o16.dtype == np.float32
    # probabilities must agree to bf16-tier tolerance after identical training
    assert np.abs(o32 - o16).max() < 0.05


def test_bf16_computation_graph():
    from deeplearning4j_tpu import ComputationGraph
    conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(1e-2))
            .compute_dtype("bfloat16")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                          loss="MCXENT"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(8))
            .build())
    g = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    s0 = g.score(DataSet(x, y))
    for _ in range(20):
        g.fit_batch(DataSet(x, y))
    assert g.score_value < 0.5 * s0
    out = np.asarray(g.output(x))
    assert out.dtype == np.float32
    for leaf in jax.tree_util.tree_leaves(g.params):
        assert leaf.dtype == np.float32
    # compute_dtype survives the config JSON round-trip (checkpoint contract)
    from deeplearning4j_tpu.nn.conf.graph_configuration import ComputationGraphConfiguration
    assert ComputationGraphConfiguration.from_json(conf.to_json()).compute_dtype == "bfloat16"


def test_score_stays_on_device_until_read():
    """The train step must not force a device->host sync; score_value syncs
    lazily (remote-TPU readbacks cost ~100ms+ each)."""
    x, y = _data()
    net = _net(None)
    net.fit_batch(DataSet(x, y))
    assert not isinstance(net._score_dev, float)   # still a device scalar
    s = net.score_value                            # first read syncs...
    assert isinstance(s, float) and np.isfinite(s)
    assert isinstance(net._score_dev, float)       # ...and caches the float


def test_bf16_lstm_keeps_f32_carry_numerics():
    """Under compute_dtype="bfloat16" the LSTM gemms run bf16 but the
    carried cell/hidden state accumulates in f32 (_lstm_scan) — a bf16
    carry compounds rounding every timestep. Forward and several TBPTT
    training steps must track the f32 model closely."""
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm

    rng = np.random.default_rng(0)
    vocab, batch, seq = 40, 8, 60
    ids = rng.integers(0, vocab, size=(batch, seq + 1))
    x = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]

    a = char_rnn_lstm(vocab_size=vocab, hidden=64, layers=2, tbptt=30)
    a.init()
    b = char_rnn_lstm(vocab_size=vocab, hidden=64, layers=2, tbptt=30,
                      compute_dtype="bfloat16")
    b.init()
    np.testing.assert_allclose(np.asarray(a.output(x)), np.asarray(b.output(x)),
                               atol=0.05)
    for net in (a, b):
        for _ in range(8):
            net.fit_batch(DataSet(x, y))
    assert abs(float(a.score_value) - float(b.score_value)) < 0.3
