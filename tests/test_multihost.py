"""Multi-host bootstrap executed with REAL multiple processes.

VERDICT r3 flagged parallel/multihost.py as never having executed with >1
process. This test runs the module docstring's recipe across two actual OS
processes (jax.distributed over a local coordinator, 2 virtual CPU devices
per process -> a 4-device global mesh): each process loads only its
process_batch_slice, assembles the global batch with host_local_to_global,
and ShardedTrainer's compiled step all-reduces gradients across the
process boundary. The resulting parameters must match single-process
full-batch training (the reference's Spark executors + parameter averaging
semantics at window 1, ParameterAveragingTrainingMaster.java:344-378).
"""
import socket
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from jax.sharding import PartitionSpec as P

    pid = int(sys.argv[1])
    from deeplearning4j_tpu.parallel import multihost
    multihost.initialize(coordinator="127.0.0.1:{port}", num_processes=2,
                         process_id=pid)
    assert multihost.process_count() == 2
    assert multihost.local_device_count() == 2
    mesh = multihost.global_mesh()  # 4 global devices on the data axis

    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.parallel.sharding import ShardedTrainer

    def build():
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="MCXENT"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    B = 32
    X = rng.normal(size=(B, 8)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, B)]

    ref = build()                      # deterministic single-process oracle
    ref.fit_batch(DataSet(X, Y))
    ref_flat = np.asarray(ref.get_flat_params())

    net = build()
    tr = ShardedTrainer(net, mesh=mesh)
    s, e = multihost.process_batch_slice(B)
    assert (e - s) == B // 2           # even split across the 2 processes
    xg, yg = multihost.host_local_to_global([X[s:e], Y[s:e]], mesh,
                                            [P("data"), P("data")])
    tr.fit_batch(DataSet(xg, yg))
    flat = np.concatenate([np.asarray(jax.device_get(l)).ravel()
                           for l in jax.tree_util.tree_leaves(net.params)])
    err = float(np.max(np.abs(flat - ref_flat)))
    assert err < 1e-5, err
    print(pid, "MULTIHOST-OK", flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_data_parallel_matches_single_process(tmp_path):
    import pathlib
    repo = str(pathlib.Path(__file__).resolve().parents[1])
    code = _WORKER.format(repo=repo, port=_free_port())
    procs = [subprocess.Popen([sys.executable, "-c", code, str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=260)
            outs.append(out)
    finally:
        # a hung worker (e.g. coordinator port collision) must not outlive
        # the test holding the port; salvage whatever output exists
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
                out, _ = p.communicate()
                print(f"--- killed hung process {i}; output:\n{out[-3000:]}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert f"{i} MULTIHOST-OK" in out
