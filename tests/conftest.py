"""Test configuration: force an 8-device virtual CPU mesh so multi-chip sharding
paths (pjit / shard_map over a Mesh) are exercised without TPU hardware.

Mirrors the reference's strategy of testing distributed semantics in-process
(reference: deeplearning4j-scaleout/spark/dl4j-spark/src/test/java/org/deeplearning4j/spark/BaseSparkTest.java:90
uses master=local[n]); here N virtual XLA CPU devices play that role.
"""
import os
import sys
from pathlib import Path

# repo root on sys.path regardless of how pytest was invoked: tests import
# repo-level helpers (tools/smoke_serving.py) that are not in the package
_ROOT = str(Path(__file__).resolve().parents[1])
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

# The environment's sitecustomize pins JAX_PLATFORMS=axon (one real TPU chip);
# the env var is overridden before import, so force CPU via the config API.
jax.config.update("jax_platforms", "cpu")

# Gradient checks follow the reference's double-precision-on-CPU strategy
# (reference: gradientcheck/GradientCheckUtil.java:29-38 requires DOUBLE dtype).
jax.config.update("jax_enable_x64", True)
