"""Device-side ingest tests (etl.device_transform + its wiring).

The load-bearing guarantee is PARITY: for every TransformProcess column op
and both normalizer kinds, the narrow path (host prefix -> packed narrow
wire batch -> jnp device_apply) must match the wide host NumPy path to
float32 tolerance on the same records — otherwise train/serve skew creeps
in between the two representations. On top of that: the DevicePrefetcher
ingest modes (transfer_dtype narrowing, device_transform, multi-stream
chunked puts, sharded placement, h2d byte accounting + ingest span), the
fused `network.set_ingest` train path (identical params to training on the
wide path; zero steady-state recompiles), the pipeline's device_ingest
mode, the serving registry's lowered per-version normalizer, and the
donation regression (scanned multistep paths must not warn "Some donated
buffers were not usable" — tools/smoke_ingest.py asserts the same on the
bench-shaped paths).
"""
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator.base import ListDataSetIterator
from deeplearning4j_tpu.etl import (DeviceIngest, DevicePrefetcher,
                                    NormalizerMinMaxScaler,
                                    NormalizerStandardize,
                                    ParallelPipelineExecutor, Schema,
                                    TransformProcess, lower_normalizer)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry


def _schema():
    return (Schema.builder().add_numeric("a", "b")
            .add_categorical("color", ["red", "green", "blue"])
            .add_integer("label").build())


def _records(n=48, seed=0):
    rng = np.random.default_rng(seed)
    return [[float(rng.uniform(0, 10)), float(rng.normal()),
             ["red", "green", "blue"][int(c)], int(c)]
            for c in rng.integers(0, 3, n)]


def _assert_parity(tp, records=None, label_columns=("label",),
                   one_hot_labels=3, normalizer=None, **kw):
    """device_apply(prepare_host(records)) == host_reference(records)."""
    ing = DeviceIngest(tp, normalizer=normalizer,
                       label_columns=list(label_columns or []),
                       one_hot_labels=one_hot_labels, **kw)
    records = records if records is not None else _records()
    narrow = ing.prepare_host(records)
    ref = ing.host_reference(records)
    dev_x = np.asarray(ing.jit_apply_features(jnp.asarray(narrow.features)))
    np.testing.assert_allclose(dev_x, ref.features, rtol=1e-5, atol=1e-5)
    if label_columns:
        dev_y = np.asarray(ing.jit_apply_labels(jnp.asarray(narrow.labels)))
        np.testing.assert_allclose(dev_y, ref.labels, rtol=1e-5, atol=1e-5)
    return ing, narrow, ref


# -------------------------------------------------------------- op parity

def test_parity_categorical_to_one_hot():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color").build())
    ing, narrow, _ = _assert_parity(tp)
    assert not ing._host_ops            # fully device-lowered
    # the one-hot expansion happens ON DEVICE: the wire carries one narrow
    # column per categorical, not |vocab| float32 columns
    assert narrow.features.shape[-1] == 3


def test_parity_categorical_to_integer():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_integer("color").build())
    _assert_parity(tp)


def test_parity_min_max_normalize():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color")
          .min_max_normalize("a", 0.0, 10.0, lo=-1.0, hi=1.0).build())
    _assert_parity(tp)


def test_parity_standardize():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color")
          .standardize("b", mean=0.3, std=1.7).build())
    _assert_parity(tp)


def test_parity_filter_rows_runs_in_host_prefix():
    tp = (TransformProcess.builder(_schema())
          .filter_rows("a", "gt", 6.0)
          .categorical_to_one_hot("color").build())
    ing, narrow, ref = _assert_parity(tp)
    # data-dependent row drop cannot trace: it must sit in the host prefix
    assert [type(o).__name__ for o in ing._host_ops] == ["FilterRows"]
    assert narrow.features.shape[0] == ref.features.shape[0] < 48


def test_parity_remove_and_rename_columns():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color")
          .remove_columns("b")
          .rename_column("a", "alpha").build())
    _assert_parity(tp)


@pytest.mark.parametrize("fn,cols,scalar", [
    ("mul", ["a", "b"], None), ("add", ["a", "b"], None),
    ("sub", ["a", "b"], None), ("div", ["a"], 3.0),
    ("log", ["a"], None), ("abs", ["b"], None)])
def test_parity_derived_column(fn, cols, scalar):
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color")
          .derived_column("d", fn, cols, scalar=scalar).build())
    # log needs strictly positive input: records draw a from U(0, 10)
    _assert_parity(tp)


def test_parity_sequence_window():
    schema = Schema.builder().add_numeric("x", "y").build()
    tp = (TransformProcess.builder(schema)
          .sequence_window(size=4, stride=2).build())
    rng = np.random.default_rng(1)
    recs = [[float(a), float(b)] for a, b in rng.normal(size=(20, 2))]
    _assert_parity(tp, records=recs, label_columns=(), one_hot_labels=None)


def test_parity_full_chain_with_normalizer_kinds():
    tp = (TransformProcess.builder(_schema())
          .filter_rows("b", "lt", -2.5)
          .categorical_to_one_hot("color")
          .derived_column("ab", "mul", ["a", "b"])
          .min_max_normalize("a", 0.0, 10.0)
          .standardize("b", 0.0, 1.0)
          .rename_column("ab", "prod").build())
    for nz in (NormalizerStandardize(), NormalizerMinMaxScaler(lo=-1, hi=1)):
        probe = DeviceIngest(tp, label_columns=["label"], one_hot_labels=3)
        nz.fit(probe.host_reference(_records(seed=7)))
        _assert_parity(tp, normalizer=nz)


def test_parity_fit_labels_normalizer_with_label_columns():
    """fit_labels=True + float label columns: the LABEL stats must ride
    into apply_labels — the host path normalizes regression targets, so
    skipping them on device would be silent train skew."""
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color").build())
    nz = NormalizerStandardize(fit_labels=True)
    probe = DeviceIngest(tp, label_columns=["label"])
    nz.fit(probe.host_reference(_records(seed=5)))
    ing, narrow, ref = _assert_parity(tp, normalizer=nz,
                                      label_columns=("label",),
                                      one_hot_labels=None)
    # and the labels really were normalized (device output != raw wire)
    assert not np.allclose(np.asarray(narrow.labels, np.float32), ref.labels)


def test_parity_mirrored_labels_with_normalizer():
    """No label columns: labels mirror features. Host transform() leaves
    mirrored labels un-normalized unless fit_labels — the device path must
    not leak FEATURE stats into them, and must apply LABEL stats iff
    fit_labels."""
    schema = (Schema.builder().add_numeric("a", "b")
              .add_categorical("color", ["red", "green", "blue"]).build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_one_hot("color").build())
    recs = [r[:3] for r in _records(seed=13)]
    for fit_labels in (False, True):
        nz = NormalizerStandardize(fit_labels=fit_labels)
        nz.fit(DeviceIngest(tp).host_reference(recs))
        ing = DeviceIngest(tp, normalizer=nz)
        narrow = ing.prepare_host(recs)
        ref = ing.host_reference(recs)
        dev_y = np.asarray(ing.jit_apply_labels(jnp.asarray(narrow.labels)))
        np.testing.assert_allclose(dev_y, ref.labels, rtol=1e-5, atol=1e-5,
                                   err_msg=f"fit_labels={fit_labels}")


# ------------------------------------------------------- normalizer lowering

@pytest.mark.parametrize("make", [
    lambda: NormalizerStandardize(fit_labels=True),
    lambda: NormalizerMinMaxScaler(lo=-2.0, hi=2.0, fit_labels=True)])
def test_lower_normalizer_apply_and_revert_round_trip(make):
    rng = np.random.default_rng(3)
    nz = make().fit(DataSet(rng.normal(2.0, 3.0, (64, 5)).astype(np.float32),
                            rng.normal(-1.0, 0.5, (64, 2)).astype(np.float32)))
    apply, revert = lower_normalizer(nz)
    x = rng.normal(2.0, 3.0, (16, 5)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(apply(jnp.asarray(x))),
                               nz.transform_features(x), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(revert(apply(jnp.asarray(x)))), x,
                               rtol=1e-3, atol=1e-3)
    lapply, lrevert = lower_normalizer(nz, labels=True)
    y = rng.normal(size=(16, 2)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(lrevert(jnp.asarray(y))),
                               nz.revert_labels(y), rtol=1e-5, atol=1e-5)
    assert np.asarray(lapply(jnp.asarray(y))).shape == y.shape


def test_lower_normalizer_requires_fitted_stats():
    with pytest.raises(RuntimeError):
        lower_normalizer(NormalizerStandardize())


# --------------------------------------------------------------- prefetcher

def test_prefetcher_transfer_dtype_narrows_wire_bytes():
    reg = MetricsRegistry()
    n, d = 8, 6
    x = np.linspace(0, 255, n * d).reshape(n, d).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
    pf = DevicePrefetcher(ListDataSetIterator([DataSet(x, y)]),
                          registry=reg, transfer_dtype=np.uint8,
                          name="narrow")
    ds = next(iter(pf))
    pf.close()
    assert str(ds.features.dtype) == "uint8"
    # the counter records what CROSSED the link: uint8 features + f32 labels
    assert reg.counter("etl_h2d_bytes_total").get() == n * d + y.nbytes


def test_prefetcher_device_transform_and_ingest_span():
    from deeplearning4j_tpu.telemetry.trace import Tracer
    reg = MetricsRegistry()
    tracer = Tracer(max_spans=64)
    x = np.arange(24, dtype=np.uint8).reshape(4, 6)
    ing = DeviceIngest(normalizer=None)     # identity feature path
    import jax
    scale = jax.jit(lambda a: a.astype(jnp.float32) / 255.0)
    pf = DevicePrefetcher(ListDataSetIterator([DataSet(x, x)]),
                          registry=reg, device_transform=scale,
                          tracer=tracer, name="dt")
    ds = next(iter(pf))
    pf.close()
    np.testing.assert_allclose(np.asarray(ds.features),
                               x.astype(np.float32) / 255.0)
    spans = [s for s in tracer.finished_spans() if s.name == "ingest"]
    assert spans and {"transfer_ms", "transform_ms", "bytes"} <= \
        set(spans[0].attributes)
    assert ing.apply_labels is not None     # touched: identity ingest builds


def test_prefetcher_multi_stream_chunked_put_matches():
    n, d = 64, 512             # > 1 MiB of float32 so chunking engages
    x = np.random.default_rng(0).normal(size=(n, d * 9)).astype(np.float32)
    y = np.ones((n, 2), np.float32)
    pf = DevicePrefetcher(ListDataSetIterator([DataSet(x, y)]),
                          registry=MetricsRegistry(), transfer_streams=4)
    ds = next(iter(pf))
    pf.close()
    np.testing.assert_array_equal(np.asarray(ds.features), x)


def test_prefetcher_sharded_mode_applies_transform_under_sharding():
    import jax
    from deeplearning4j_tpu.parallel.sharding import make_mesh
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    scale = jax.jit(lambda a: a.astype(jnp.float32) * 2.0)
    x = np.arange(12, dtype=np.uint8).reshape(4, 3)
    pf = DevicePrefetcher(ListDataSetIterator([DataSet(x, x)]), mesh=mesh,
                          registry=MetricsRegistry(), device_transform=scale)
    ds = next(iter(pf))
    pf.close()
    np.testing.assert_allclose(np.asarray(ds.features),
                               x.astype(np.float32) * 2.0)


# ------------------------------------------------------------- fused fit

def _tabular_net(n_features, seed=0):
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, Adam)
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(n_features)).build())
    return MultiLayerNetwork(conf).init()


def test_set_ingest_trains_identically_to_host_path():
    """The whole point: raw narrow batches + fused device ingest produce
    the SAME parameters as preprocessed float batches — through fit_batch
    AND the scanned multistep executable."""
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color")
          .min_max_normalize("a", 0.0, 10.0).build())
    ing = DeviceIngest(tp, label_columns=["label"], one_hot_labels=3)
    recs = _records(192, seed=5)
    narrow = [ing.prepare_host(recs[i * 32:(i + 1) * 32]) for i in range(6)]
    wide = [ing.host_reference(recs[i * 32:(i + 1) * 32]) for i in range(6)]
    n_feat = wide[0].features.shape[-1]

    dev = _tabular_net(n_feat).set_ingest(ing)
    dev.fit(ListDataSetIterator(narrow), epochs=2, steps_per_execution=3)
    host = _tabular_net(n_feat)
    host.fit(ListDataSetIterator(wide), epochs=2, steps_per_execution=3)
    for layer in dev.params:
        for k in dev.params[layer]:
            np.testing.assert_allclose(
                np.asarray(dev.params[layer][k]),
                np.asarray(host.params[layer][k]), rtol=2e-4, atol=2e-4)


def test_graph_multi_output_ingest_trains_identically():
    """ComputationGraph.set_ingest with TWO output heads: labels[0] goes
    through apply_labels, and labels[1:] must still land on the param dtype
    (the non-ingest _prep_batch cast) — so both paths train identically."""
    import jax
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    ComputationGraph, MultiDataSet, Adam)

    def conf():
        return (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("dense", DenseLayer(n_out=16, activation="relu"),
                           "in")
                .add_layer("cls", OutputLayer(n_out=3, activation="softmax",
                                              loss="MCXENT"), "dense")
                .add_layer("reg", OutputLayer(n_out=2, activation="identity",
                                              loss="MSE"), "dense")
                .set_outputs("cls", "reg")
                .set_input_types(InputType.feed_forward(4))
                .build())

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    ids = rng.integers(0, 3, 64).astype(np.int32)
    y_cls = np.eye(3, dtype=np.float32)[ids]
    y_reg = rng.normal(size=(64, 2)).astype(np.float64)  # exercises the cast

    g_ref = ComputationGraph(conf()).init()
    seed_params = jax.tree_util.tree_map(lambda a: np.array(a), g_ref.params)
    g_ing = ComputationGraph(conf()).init(
        params=jax.tree_util.tree_map(lambda a: np.array(a), seed_params))
    g_ref.fit([MultiDataSet([x], [y_cls, y_reg])], epochs=3)
    g_ing.set_ingest(DeviceIngest(one_hot_labels=3))
    g_ing.fit([MultiDataSet([x], [ids, y_reg])], epochs=3)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref.params),
                    jax.tree_util.tree_leaves(g_ing.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_set_ingest_zero_steady_state_recompiles():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color").build())
    ing = DeviceIngest(tp, label_columns=["label"], one_hot_labels=3)
    recs = _records(96, seed=9)
    narrow = [ing.prepare_host(recs[i * 32:(i + 1) * 32]) for i in range(3)]
    net = _tabular_net(narrow[0].features.shape[-1] + 2).set_ingest(ing)
    from deeplearning4j_tpu.telemetry.registry import get_registry
    compiles = get_registry().counter("jit_compiles_total")
    net.fit(ListDataSetIterator(narrow), epochs=1)
    before = compiles.get()
    net.fit(ListDataSetIterator(narrow), epochs=3)
    assert compiles.get() == before, "steady-state recompile with ingest"


def test_pipeline_device_ingest_mode_emits_narrow_and_exposes_ingest():
    from deeplearning4j_tpu.datasets.records.reader import (
        CollectionRecordReader)
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color").build())
    recs = _records(64, seed=11)
    nz = NormalizerStandardize()
    probe = DeviceIngest(tp, label_columns=["label"], one_hot_labels=3)
    nz.fit(probe.host_reference(recs))
    pipe = ParallelPipelineExecutor(
        CollectionRecordReader(recs), tp, batch_size=16, workers=2,
        normalizer=nz, label_columns=["label"], one_hot_labels=3,
        device_ingest=True, name="ingest_pipe", registry=MetricsRegistry())
    batches = list(pipe)
    pipe.close()
    assert len(batches) == 4
    # narrow on the wire: float32 packed features, uint8 class ids — and the
    # normalizer was NOT applied on host (it is fused into ingest instead)
    assert batches[0].features.shape == (16, 3)
    assert str(batches[0].labels.dtype) == "uint8"
    dev = np.asarray(pipe.ingest.jit_apply_features(
        jnp.asarray(batches[0].features)))
    ref = pipe.ingest.host_reference(recs[:16])
    np.testing.assert_allclose(dev, ref.features, rtol=1e-5, atol=1e-5)


def test_pipeline_device_ingest_rejects_bad_configs():
    tp = (TransformProcess.builder(_schema())
          .categorical_to_one_hot("color").build())
    from deeplearning4j_tpu.datasets.records.reader import (
        CollectionRecordReader)
    with pytest.raises(ValueError):
        ParallelPipelineExecutor(CollectionRecordReader([]), None,
                                 device_ingest=True,
                                 registry=MetricsRegistry())
    with pytest.raises(ValueError):
        ParallelPipelineExecutor(CollectionRecordReader([]), tp,
                                 device_ingest=True,
                                 assemble=lambda r: None,
                                 registry=MetricsRegistry())


# ---------------------------------------------------------------- serving

def test_serving_version_lowers_normalizer_to_device():
    from deeplearning4j_tpu.serving.registry import ModelVersion
    rng = np.random.default_rng(2)
    nz = NormalizerStandardize().fit(
        DataSet(rng.normal(3.0, 2.0, (128, 4)).astype(np.float32), None))
    mv = ModelVersion("v1", model=object(), transform=nz)
    x = rng.normal(3.0, 2.0, (8, 4)).astype(np.float32)
    out = mv.transform_features_device(x)
    assert mv._device_transform is not False    # actually lowered
    np.testing.assert_allclose(np.asarray(out), nz.transform_features(x),
                               rtol=1e-5, atol=1e-5)
    assert str(np.asarray(out).dtype) == "float32"
    # non-lowerable transform falls back to the host path
    mv2 = ModelVersion("v2", model=object(), transform=lambda a: a * 2)
    np.testing.assert_allclose(mv2.transform_features_device(x), x * 2)


# ------------------------------------------------------------------ smoke

def test_smoke_ingest_tool():
    """uint8 CSV + image batches -> device transform -> fit: zero
    steady-state recompiles, no donation warnings, narrow bytes on the wire
    (fast variant of tools/smoke_ingest.py, mirroring the smoke_etl
    wiring)."""
    import tools.smoke_ingest as smoke
    out = smoke.run(n_rows=256, epochs=5)
    assert out["tabular_accuracy"] > 0.9 and out["image_accuracy"] > 0.9
    assert out["tabular_recompiles"] == 0 and out["image_recompiles"] == 0
    assert out["donation_warnings"] == 0
    assert out["etl_h2d_bytes_total"] > 0


# -------------------------------------------------------------- donation

def test_scanned_paths_donate_cleanly():
    """The BENCH_r05 warning — 'Some donated buffers were not usable:
    float32[64,256] x4' from the scanned TBPTT executable — must stay gone:
    the final carries are now scan outputs, so the donated carry buffers
    alias them."""
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm
    net = char_rnn_lstm(vocab_size=12, hidden=16, layers=2, tbptt=5)
    net.init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(4, 11))
    x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        plan = net.prepare_steps([ds] * 3)
        assert plan is not None and plan[0] == "tbptt"
        net.fit_prepared(plan)
        net2 = _tabular_net(4)
        flat = DataSet(np.random.default_rng(1).normal(size=(8, 4))
                       .astype(np.float32),
                       np.eye(3, dtype=np.float32)[np.arange(8) % 3])
        net2.fit(ListDataSetIterator([flat] * 4), steps_per_execution=2)
    donation = [w for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], [str(w.message) for w in donation]
