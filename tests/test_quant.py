"""Bytes diet (ROADMAP item 3, ISSUE 15): low-bit optimizer moments riding
inside the ZeRO flatten-pad layout, and int8 weight-quantized serving
executables — both through nn/quant.py, the one designated quant module.

Contracts under test:
- MomentCodec round-trips are EXACT-idempotent (pow2 scales), so conversion
  chains (checkpoint -> restore -> re-shard -> re-shard) replay codes
  bit-for-bit, at any shard count;
- q8/bf16 moments train to parity-tolerance vs f32 moments with per-device
  moment bytes cut >= 3.5x (q8) / 2x (bf16) at the same shard count, with
  donation intact and zero steady-state recompiles on every train path;
- int8 weight quantization serves within the accuracy-parity gate, HBM
  param bytes cut ~4x, zips stay f32, training refuses quantized weights,
  and the deploy gate fails CLOSED (breach -> f32 restored, old version
  keeps serving).
"""
import os
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet, Adam)
from deeplearning4j_tpu.datasets.iterator.base import ListDataSetIterator
from deeplearning4j_tpu.nn.quant import (MomentCodec, QuantGate,
                                         QuantParityError, WeightQuant,
                                         quantize_model_weights)
from deeplearning4j_tpu.parallel.sharding import make_mesh, ShardedTrainer
from deeplearning4j_tpu.parallel.zero import (ZeroUpdater, moment_bytes,
                                              per_device_bytes)


def _toy(n=64, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    y = np.argmax(X @ w, axis=1)
    return X, np.eye(nout, dtype=np.float32)[y]


def _conf(nin=8, nout=3, seed=42, hidden=16, updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())


def _canonical_moments(net):
    st = net.opt_state
    z = getattr(net, "_zero", None)
    if z is not None:
        st = z.to_canonical(st, net.params)
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(st)[0]:
        if hasattr(leaf, "shape"):
            out["/".join(str(k) for k in path)] = np.asarray(leaf)
    return out


def _reshard(net, n, moment_dtype="q8"):
    return ShardedTrainer(net, mesh=make_mesh(n_data=n,
                                              devices=jax.devices()[:n]),
                          shard_update=True, moment_dtype=moment_dtype)


# ----------------------------------------------------------------- codec

def test_moment_codec_q8_roundtrip_exact_idempotent():
    """decode(encode(decode(x))) == decode(x) BIT-FOR-BIT: pow2 scales make
    every decode an exact float op and every re-encode reproduce the same
    scale — the property that keeps re-shard chains drift-free without
    stochastic rounding."""
    c = MomentCodec("q8", n_shards=8, block=128)
    rng = np.random.default_rng(3)
    v = np.concatenate([rng.normal(0, 1e-4, 300), np.zeros(130),
                        rng.normal(0, 7.0, 96), [1e-30, -1e-30]])
    L = -(-len(v) // 8) * 8
    v = jnp.asarray(np.pad(v, (0, L - len(v))).astype(np.float32))
    e1 = c.encode(v)
    d1 = c.decode(e1, L)
    e2 = c.encode(d1)
    np.testing.assert_array_equal(np.asarray(e1["qcodes"]),
                                  np.asarray(e2["qcodes"]))
    np.testing.assert_array_equal(np.asarray(e1["qscale"]),
                                  np.asarray(e2["qscale"]))
    np.testing.assert_array_equal(np.asarray(c.decode(e2, L)),
                                  np.asarray(d1))


def test_moment_codec_q8_no_small_value_annihilation():
    """The reason the codes are fp8-e4m3 and not linear int8: entries many
    orders below the block absmax must survive (a zeroed second moment
    divides the update by eps and the run detonates). Entries down to
    absmax/1e4 keep ~6% relative error."""
    c = MomentCodec("q8", n_shards=1, block=128)
    v = np.zeros(128, np.float32)
    v[0] = 1.0                     # block absmax
    v[1] = 1e-4                    # 4 orders below
    v[2] = -3e-3
    d = np.asarray(c.decode(c.encode(jnp.asarray(v)), 128))
    assert d[1] != 0.0 and abs(d[1] - 1e-4) / 1e-4 < 0.07
    assert abs(d[2] + 3e-3) / 3e-3 < 0.07
    assert abs(d[0] - 1.0) < 0.07


def test_moment_codec_bf16_roundtrip():
    c = MomentCodec("bf16", n_shards=4)
    v = jnp.asarray(np.random.default_rng(0).normal(size=64).astype(np.float32))
    e = c.encode(v)
    assert e.dtype == jnp.bfloat16
    d = c.decode(e, 64)
    assert d.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(c.decode(c.encode(d), 64)),
                                  np.asarray(d))


# ------------------------------------------------- training with low-bit

@pytest.mark.parametrize("md,tol", [("bf16", 5e-3), ("q8", 5e-2)])
def test_low_bit_moment_training_parity_tolerance(md, tol):
    """ISSUE satellite: a quantized-moment run reaches parity-tolerance vs
    f32 moments on a small model — same data, same seed, final params and
    score track the f32-moment run."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    a = MultiLayerNetwork(_conf()).init()
    tra = ShardedTrainer(a, mesh=make_mesh(n_data=8), shard_update=True)
    b = MultiLayerNetwork(_conf()).init()
    trb = ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True,
                         moment_dtype=md)
    for _ in range(12):
        tra.fit_batch(ds)
        trb.fit_batch(ds)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               atol=tol, rtol=0)
    assert abs(a.score_value - b.score_value) < tol
    assert np.isfinite(b.score_value)


def test_q8_moment_bytes_at_least_3p5x_smaller_and_gauge_reports():
    """ISSUE acceptance: `opt_moment_bytes_per_device` drops >= 3.5x with
    8-bit moments vs f32 at the SAME shard count (and >= 2x for bf16), and
    the gauge carries the dtype attribution."""
    def conf():
        # two hidden-256 layers: weight leaves big enough that the q8
        # codes' block*n_shards pad granule is noise, like the real models
        # the bench measures (resnet50: 3.9x)
        return (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=256, activation="relu"))
                .layer(DenseLayer(n_out=256, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="MCXENT"))
                .input_type(InputType.feed_forward(8)).build())

    f = MultiLayerNetwork(conf()).init()
    ShardedTrainer(f, mesh=make_mesh(n_data=8), shard_update=True)
    mf = moment_bytes(f.opt_state)

    q = MultiLayerNetwork(conf()).init()
    ShardedTrainer(q, mesh=make_mesh(n_data=8), shard_update=True,
                   moment_dtype="q8")
    mq = moment_bytes(q.opt_state)
    assert mq * 3.5 <= mf, (mf, mq)

    h = MultiLayerNetwork(conf()).init()
    ShardedTrainer(h, mesh=make_mesh(n_data=8), shard_update=True,
                   moment_dtype="bf16")
    assert moment_bytes(h.opt_state) * 2 <= mf

    from deeplearning4j_tpu.telemetry.registry import get_registry
    series = {}
    for labels, value in get_registry().gauge(
            "opt_moment_bytes_per_device").series():
        series[(labels.get("mode"), labels.get("dtype"))] = value
    assert series[("zero", "q8")] == mq
    assert series[("zero", "f32")] == mf


def test_q8_every_train_path_donation_clean_no_retrace():
    """ISSUE acceptance: zero new donation warnings AND zero steady-state
    recompiles on the quantized paths — std jit step, scanned multistep,
    and both TBPTT paths all run with q8 moments; re-running each
    executable leaves its XLA cache size flat."""
    sets = [DataSet(*_toy(n=32, seed=s)) for s in range(8)]
    net = MultiLayerNetwork(_conf()).init()
    tr = ShardedTrainer(net, mesh=make_mesh(n_data=8), shard_update=True,
                        moment_dtype="q8")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        tr.fit_batch(sets[0])                              # std jit step
        tr.fit(ListDataSetIterator(sets), steps_per_execution=4)  # scanned
        sizes0 = {k: f._cache_size() for k, f in net._jit_cache.items()
                  if hasattr(f, "_cache_size")}
        tr.fit_batch(sets[0])
        tr.fit(ListDataSetIterator(sets), steps_per_execution=4)
        sizes1 = {k: f._cache_size() for k, f in net._jit_cache.items()
                  if hasattr(f, "_cache_size")}
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    assert sizes0 == sizes1, (sizes0, sizes1)

    # both TBPTT paths (per-window + scanned multi_tbptt)
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm
    rnn = char_rnn_lstm(vocab_size=12, hidden=16, layers=2, tbptt=5).init()
    rnn.set_update_sharding(ZeroUpdater(make_mesh(n_data=8),
                                        moment_dtype="q8"))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(8, 21))
    x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
    dsr = DataSet(jnp.asarray(x), jnp.asarray(y))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rnn.fit_batch(dsr)
        plan = rnn.prepare_steps([dsr] * 2)
        assert plan is not None and plan[0] == "tbptt"
        rnn.fit_prepared(plan)
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    assert np.isfinite(float(rnn.score_value))


# ------------------------------------------------------- re-shard chains

def test_q8_reshard_chain_8_4_8_bitwise():
    """ISSUE satellite: quantized state converts through the canonical
    layout across re-shard chains with ZERO drift — 8 -> 4 -> 8 leaves
    every canonical moment bit-identical (exact-idempotent codec + blocks
    anchored at canonical offset 0)."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(4):
        tr.fit_batch(ds)
    before = _canonical_moments(net)
    tr = _reshard(net, 4)          # elastic shrink...
    tr = _reshard(net, 8)          # ...and regrow
    after = _canonical_moments(net)
    assert before.keys() == after.keys()
    for k in before:
        np.testing.assert_array_equal(before[k], after[k], err_msg=k)
    # degenerate single-shard hop too
    tr = _reshard(net, 1)
    tr = _reshard(net, 8)
    final = _canonical_moments(net)
    for k in before:
        np.testing.assert_array_equal(before[k], final[k], err_msg=k)


def test_q8_elastic_shrink_grow_with_training_bounded_drift():
    """The full elastic arc WITH steps at each topology (8 -> 4 -> 8):
    params track a fixed-8-shard q8 oracle within tolerance — momentum is
    carried through both hops, not reset."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    otr = _reshard(oracle, 8)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 4)
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 8)
    for _ in range(2):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(), atol=5e-2, rtol=0)
    a, b = _canonical_moments(net), _canonical_moments(oracle)
    assert a.keys() == b.keys()
    for k in a:
        assert np.all(np.isfinite(a[k])), k


def test_elastic_trainer_preserves_q8_codec_across_reshard(tmp_path):
    """ElasticTrainer(moment_dtype="q8"): a chaos preemption re-shards the
    live run and the NEW ShardedTrainer keeps the q8 codec — the bytes diet
    survives topology changes."""
    from deeplearning4j_tpu.elastic import ElasticTrainer
    from deeplearning4j_tpu.resilience.chaos import FaultPlan, FaultRule
    from deeplearning4j_tpu.telemetry.health import HealthMonitor
    from deeplearning4j_tpu.train.fault_tolerance import CheckpointConfig

    X, Y = _toy()
    it = ListDataSetIterator([DataSet(X, Y)] * 8)
    plan = FaultPlan([FaultRule("preempt", target="w3", at_step=4,
                                name="kill-w3")])
    trainer = ElasticTrainer(lambda: MultiLayerNetwork(_conf()).init(),
                             CheckpointConfig(tmp_path / "ck", frequency=0),
                             devices=jax.devices()[:4], plan=plan,
                             monitor=HealthMonitor(), moment_dtype="q8")
    trainer.fit(it, epochs=1)
    assert trainer.reshards == 1 and trainer._alive == ["w0", "w1", "w2"]
    net = trainer._net()
    assert net._zero is not None and net._zero.moment_dtype == "q8"
    assert np.isfinite(net.score_value)


def test_fault_tolerant_trainer_resumes_q8_run_on_fewer_replicas(tmp_path):
    """The async snapshot-then-write checkpoint path canonicalizes q8
    moments (to_canonical decodes before the host snapshot): an 8-shard
    q8 run's checkpoint resumes in a 4-shard q8 trainer with the codec
    re-applied."""
    from deeplearning4j_tpu.train.fault_tolerance import (CheckpointConfig,
                                                          FaultTolerantTrainer)
    X, Y = _toy()
    ds = DataSet(X, Y)
    ckdir = str(tmp_path / "ck")
    t1 = FaultTolerantTrainer(
        lambda: _reshard(MultiLayerNetwork(_conf()).init(), 8),
        CheckpointConfig(ckdir, frequency=2))
    t1.fit(ListDataSetIterator([ds] * 4), epochs=1)
    t2 = FaultTolerantTrainer(
        lambda: _reshard(MultiLayerNetwork(_conf()).init(), 4),
        CheckpointConfig(ckdir, frequency=2))
    assert t2.resumed
    t2.fit(ListDataSetIterator([ds] * 4), epochs=2)
    net = t2._net()
    assert net.iteration_count == 8
    assert net._zero is not None and net._zero.moment_dtype == "q8"
    assert np.isfinite(net.score_value)


def test_q8_checkpoint_restores_at_different_shard_count(tmp_path):
    """Canonical checkpoint format UNCHANGED: a q8-moment run writes the
    same per-param f32 updater state every serializer stores; the restore
    re-shards AND re-quantizes at a different replica count and resumes
    with momentum intact (near-bitwise: the restore replays the exact
    decoded moments)."""
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    X, Y = _toy()
    ds = DataSet(X, Y)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(4):
        tr.fit_batch(ds)
    path = str(tmp_path / "q8.zip")
    ModelSerializer.write_model(net, path)

    restored = ModelSerializer.restore(path)
    # canonical layout: every >=1-D opt leaf has a param's exact shape/f32
    pshapes = {tuple(l.shape) for l in
               jax.tree_util.tree_leaves(restored.params)}
    for leaf in jax.tree_util.tree_leaves(restored.opt_state):
        if getattr(leaf, "ndim", 0) >= 1:
            assert tuple(leaf.shape) in pshapes
            assert leaf.dtype == jnp.float32
    tr4 = _reshard(restored, 4)
    for _ in range(3):
        tr4.fit_batch(ds)
        tr.fit_batch(ds)
    np.testing.assert_allclose(net.get_flat_params(),
                               restored.get_flat_params(),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------------ int8 weights

def _trained_net(seed=7, steps=25, hidden=64, nin=16, nout=5):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    Y = np.eye(nout, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    net = MultiLayerNetwork(_conf(nin=nin, nout=nout, seed=seed,
                                  hidden=hidden)).init()
    for _ in range(steps):
        net.fit_batch(DataSet(X, Y))
    return net, X, Y


def test_weight_quant_parity_and_bytes():
    """Per-channel int8: top-1 preserved, outputs within the default gate,
    per-device param bytes cut >= 3x (weights dominate this model)."""
    net, X, _ = _trained_net()
    ref = np.asarray(net.output(X))
    b_f32 = per_device_bytes(net.params)
    net.quantize_weights("int8")
    q = np.asarray(net.output(X))
    b_q = per_device_bytes(net.params)
    assert b_q * 3 <= b_f32, (b_f32, b_q)
    assert np.mean(np.argmax(ref, 1) == np.argmax(q, 1)) >= 0.99
    assert np.max(np.abs(ref - q)) / np.max(np.abs(ref)) < 0.05
    # int8 codes really are the executable operands (HBM-resident narrow)
    assert net.params["0"]["W"].dtype == jnp.int8
    # biases/norm leaves stay f32
    assert net.params["0"]["b"].dtype != jnp.int8


def test_weight_quant_refuses_training_and_dequantize_restores():
    net, X, Y = _trained_net()
    ref = np.asarray(net.output(X))
    net.quantize_weights("int8")
    with pytest.raises(RuntimeError, match="serving-only"):
        net.fit_batch(DataSet(X, Y))
    with pytest.raises(RuntimeError, match="serving-only"):
        net.prepare_steps([DataSet(X, Y)] * 2)
    net.dequantize_weights()
    np.testing.assert_allclose(np.asarray(net.output(X)), ref, rtol=1e-6)
    net.fit_batch(DataSet(X, Y))    # trains again after restore


def test_weight_quant_zip_stays_f32(tmp_path):
    """Serializers write the f32 backup, never the codes: a restore of a
    quantized model's zip is a plain full-precision model."""
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    net, X, _ = _trained_net()
    f32_params = {k: {kk: np.asarray(vv) for kk, vv in v.items()}
                  for k, v in net.params.items()}
    net.quantize_weights("int8")
    path = str(tmp_path / "q.zip")
    ModelSerializer.write_model(net, path)
    r = ModelSerializer.restore(path)
    for lk, sub in r.params.items():
        for k, leaf in sub.items():
            assert jnp.issubdtype(leaf.dtype, jnp.floating), (lk, k)
            np.testing.assert_allclose(np.asarray(leaf), f32_params[lk][k],
                                       rtol=1e-6)


def test_weight_quant_zero_steady_state_recompiles():
    """The quantized output executable compiles once per (shape, mask)
    family and never again — the serving no-recompile invariant holds for
    int8 weights."""
    net, X, _ = _trained_net()
    net.quantize_weights("int8")
    net.output(X)
    key = ("output", False, False)
    size0 = net._jit_cache[key]._cache_size()
    for _ in range(3):
        net.output(X)
    assert net._jit_cache[key]._cache_size() == size0 == 1


def test_weight_quant_computation_graph_and_decode_parity():
    """ComputationGraph quantizes through the same mixin, and the decode
    engine consumes the narrow weights: greedy KV decode on the quantized
    transformer matches the naive quantized full-forward token-for-token."""
    from deeplearning4j_tpu.zoo.models import transformer_lm
    net = transformer_lm(vocab_size=32, d_model=32, n_layers=2, n_heads=2)
    net.init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32, size=(8, 13))
    x = np.eye(32, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(32, dtype=np.float32)[ids[:, 1:]]
    for _ in range(8):
        net.fit_batch(DataSet(x, y))
    net.quantize_weights("int8")
    prompt = list(rng.integers(0, 32, 6))
    toks = net.generate(prompt, max_new_tokens=5)
    seq = list(prompt)
    for t in toks:
        out = np.asarray(net.output(
            np.eye(32, dtype=np.float32)[np.asarray(seq)][None]))
        assert int(np.argmax(out[0, -1])) == t
        seq.append(t)


def test_quantize_model_weights_gate_fails_closed():
    """A breached gate restores the f32 weights and raises — the model
    never serves half-quantized."""
    net, X, _ = _trained_net()
    ref = np.asarray(net.output(X))
    with pytest.raises(QuantParityError):
        quantize_model_weights(net, parity_inputs=X[:16],
                               gate=QuantGate(max_rel_delta=0.0))
    assert net._wq is None
    np.testing.assert_allclose(np.asarray(net.output(X)), ref, rtol=1e-6)
    # and a passing gate reports parity
    report = quantize_model_weights(net, parity_inputs=X[:16])
    assert report["gated"] and report["top1_agreement"] >= 0.97


# ----------------------------------------------------------- serving

def test_serving_deploy_quantize_int8_end_to_end(tmp_path):
    """POST /deploy {"quantize": "int8"}: parity-gated quantization before
    the warm-up, /predict parity vs the f32 deploy, /models carries the
    quantized+parity attribution, and a strict-gate breach fails the
    deploy with the old version still serving f32."""
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.util.http import get_json, post_json
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    net, X, _ = _trained_net()
    ModelSerializer.write_model(net, os.path.join(tmp_path, "v1.zip"))
    ModelSerializer.write_model(net, os.path.join(tmp_path, "v2.zip"))
    srv = ServingServer(scan_dir=str(tmp_path), alert_interval_s=0).start()
    try:
        url = srv.url
        post_json(url + "/deploy", {"version": "v1"})
        r1 = post_json(url + "/predict", {"data": X[:4].tolist()})
        r = post_json(url + "/deploy",
                      {"version": "v2", "quantize": "int8",
                       "parity_inputs": X[:32].tolist()})
        assert r["quantized"] == "int8" and r["parity"]["gated"]
        assert r["parity"]["top1_agreement"] >= 0.97
        r2 = post_json(url + "/predict", {"data": X[:4].tolist()})
        d = np.max(np.abs(np.asarray(r1["prediction"])
                          - np.asarray(r2["prediction"])))
        assert d < 0.05 and r2["version"] == "v2"
        info = {m["version"]: m for m in get_json(url + "/models")["models"]}
        assert info["v2"]["quantized"] == "int8"
        assert info["v1"]["quantized"] is None
    finally:
        srv.stop()


def test_serving_deploy_quantize_breach_keeps_old_version(tmp_path):
    """Gate breach on deploy: 400 to the caller, the candidate version is
    restored to f32, the previously active version keeps serving."""
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.util.http import post_json
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    import urllib.error

    net, X, _ = _trained_net()
    ModelSerializer.write_model(net, os.path.join(tmp_path, "v1.zip"))
    ModelSerializer.write_model(net, os.path.join(tmp_path, "v2.zip"))
    srv = ServingServer(scan_dir=str(tmp_path), alert_interval_s=0,
                        quant_gate=QuantGate(max_rel_delta=0.0)).start()
    try:
        url = srv.url
        post_json(url + "/deploy", {"version": "v1"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_json(url + "/deploy",
                      {"version": "v2", "quantize": "int8",
                       "parity_inputs": X[:16].tolist()})
        assert ei.value.code == 400
        assert srv.registry.active_version == "v1"
        mv2 = srv.registry.get("v2")
        assert mv2.quantized is None and mv2.model._wq is None
        r = post_json(url + "/predict", {"data": X[:4].tolist()})
        assert r["version"] == "v1"
    finally:
        srv.stop()


def test_smoke_quant_tool():
    """ISSUE satellite wired as tier-1: train with 8-bit moments ->
    checkpoint -> restore at a different shard count -> deploy the zip
    int8-quantized -> /predict parity within the gate, zero steady-state
    recompiles, zero donation warnings (tools/smoke_quant.py, mirroring
    the smoke_ingest wiring)."""
    import tools.smoke_quant as smoke
    out = smoke.run(steps=25)
    assert out["moment_bytes_reduction_x"] >= 3.5
    assert out["q8_train_accuracy"] > 0.9
    assert out["parity"]["top1_agreement"] >= 0.97
    assert out["predict_rel_delta"] < 0.1
    assert out["steady_state_recompiles"] == 0
    assert out["donation_warnings"] == 0


def test_serving_quantized_deploy_by_name_synthesizes_parity(tmp_path):
    """Deploy-by-name + quantize with NO explicit parity rows: the zip in
    scan_dir is not registered yet, so the parity-input synthesis must
    resolve it (the same by-name load registry.deploy would do later)
    instead of KeyError-ing — quantized by-name deploys work like plain
    ones."""
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    net, X, _ = _trained_net()
    srv = ServingServer(scan_dir=str(tmp_path), alert_interval_s=0)
    # lands AFTER the startup scan -> unregistered until deploy-by-name
    ModelSerializer.write_model(net, os.path.join(tmp_path, "late.zip"))
    srv.deploy("late", quantize="int8")        # parity rows synthesized
    mv = srv.registry.get("late")
    assert mv.quantized == "int8" and mv.parity["gated"]
    assert srv.registry.active_version == "late"


def test_deploy_warmup_failure_unquantizes(tmp_path):
    """A warm-up failure AFTER a successful quantize must restore the f32
    weights: otherwise a later plain deploy(v) silently serves int8 weights
    that deploy never asked for."""
    from deeplearning4j_tpu.serving.registry import ModelRegistry

    net, X, _ = _trained_net()
    reg = ModelRegistry()
    reg.register("v1", net)

    def bad_warmup(model):
        raise RuntimeError("warm-up exploded")

    with pytest.raises(RuntimeError, match="warm-up exploded"):
        reg.deploy("v1", warmup=bad_warmup, quantize="int8",
                   parity_inputs=X[:16])
    mv = reg.get("v1")
    assert mv.quantized is None and mv.parity is None
    assert net._wq is None                      # f32 restored
    reg.deploy("v1")                            # plain deploy serves f32
    assert net.params["0"]["W"].dtype != jnp.int8


def test_sharded_trainer_refuses_quantized_model():
    """The 'serving-only' contract holds through ShardedTrainer too — the
    clear RuntimeError, not a jax.grad dtype error from int8 leaves."""
    net, X, Y = _trained_net()
    net.quantize_weights("int8")
    tr = ShardedTrainer(net, mesh=make_mesh(n_data=8))
    with pytest.raises(RuntimeError, match="serving-only"):
        tr.fit_batch(DataSet(X, Y))


def test_registry_subscriber_applies_quantized_deploy(tmp_path):
    """Fleet half: a broker-fanned deploy event carrying quantize="int8"
    (what FleetFrontend's /deploy broadcast publishes) brings a
    late-joining replica up with the SAME int8 executables, its own parity
    gate included."""
    from deeplearning4j_tpu.serving.frontend import RegistrySubscriber
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    net, X, _ = _trained_net()
    ModelSerializer.write_model(net, os.path.join(tmp_path, "v1.zip"))
    srv = ServingServer(scan_dir=str(tmp_path), alert_interval_s=0)
    sub = RegistrySubscriber(srv)        # apply-only (no broker loop)
    assert sub.apply({"kind": "deploy", "version": "v1",
                      "quantize": "int8",
                      "parity_inputs": X[:16].tolist()})
    assert srv.registry.active_version == "v1"
    mv = srv.registry.get("v1")
    assert mv.quantized == "int8" and mv.parity["gated"]


def test_model_version_quantize_idempotent_and_conflicts():
    from deeplearning4j_tpu.serving.registry import ModelVersion
    net, X, _ = _trained_net()
    mv = ModelVersion("v1", net)
    rep = mv.quantize("int8", parity_inputs=X[:16])
    assert mv.quantized == "int8"
    assert mv.quantize("int8") == rep      # idempotent per dtype
    with pytest.raises(ValueError):
        mv.quantize("int4")
