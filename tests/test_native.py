"""Native IO runtime tests (deeplearning4j_tpu/native — the TPU build's
analog of the reference's native data path, SURVEY.md §2.9). Skipped
gracefully only if no C++ toolchain exists; in this environment g++ is
guaranteed, so the build must succeed."""
import gzip
import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native


def test_native_builds_and_loads():
    lib = native.load()
    assert lib is not None, "g++ is present in this environment; build must work"
    assert lib.dl4j_io_version() == 1


def test_csv_parse_parity_and_fallback(tmp_path):
    rng = np.random.default_rng(0)
    m = rng.normal(size=(50, 7)).astype(np.float32)
    lines = "\n".join(",".join(f"{v:.6g}" for v in row) for row in m)
    parsed = native.csv_parse(lines.encode())
    assert parsed is not None
    np.testing.assert_allclose(parsed, m, rtol=1e-5)
    # header skipping
    parsed2 = native.csv_parse(("a,b,c,d,e,f,g\n" + lines).encode(),
                               skip_lines=1)
    np.testing.assert_allclose(parsed2, m, rtol=1e-5)
    # quoted / non-numeric content -> None (Python csv fallback)
    assert native.csv_parse(b'1,"two",3\n') is None
    assert native.csv_parse(b"1,2\n3,4,5\n") is None  # ragged


def test_csv_record_reader_uses_native_fast_path(tmp_path):
    from deeplearning4j_tpu.datasets.records.reader import CSVRecordReader
    p = tmp_path / "data.csv"
    p.write_text("1,2,3\n4,5,6\n")
    r = CSVRecordReader().initialize(str(p))
    assert getattr(r, "_native", False) is True
    assert r.next_record() == [1.0, 2.0, 3.0]
    assert r.next_record() == [4.0, 5.0, 6.0]
    # non-numeric file falls back to the general parser, same contract
    p2 = tmp_path / "mixed.csv"
    p2.write_text('x,"y z",3\n')
    r2 = CSVRecordReader().initialize(str(p2))
    assert getattr(r2, "_native", True) is False
    assert r2.next_record() == ["x", "y z", 3.0]


def test_idx_decode_parity(tmp_path):
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, size=(5, 4, 3), dtype=np.uint8)
    buf = struct.pack(">IIII", 2051, 5, 4, 3) + imgs.tobytes()
    out = native.idx_read(buf)
    np.testing.assert_array_equal(out, imgs)
    labels = np.array([1, 2, 3], np.uint8)
    lbuf = struct.pack(">II", 2049, 3) + labels.tobytes()
    np.testing.assert_array_equal(native.idx_read(lbuf), labels)
    assert native.idx_read(b"\x00\x00\x0d\x01" + b"\x00" * 8) is None  # int32 type

    # the MNIST fetcher path consumes these through the native decoder
    from deeplearning4j_tpu.datasets.fetchers.mnist import _read_idx_images
    gz = tmp_path / "imgs.gz"
    with gzip.open(gz, "wb") as f:
        f.write(buf)
    np.testing.assert_array_equal(_read_idx_images(str(gz)), imgs)


def test_gather_normalize_one_hot_parity():
    rng = np.random.default_rng(2)
    src = rng.normal(size=(1000, 17)).astype(np.float32)
    idx = rng.integers(0, 1000, 333)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])
    # multithreaded path
    big_idx = rng.integers(0, 1000, 4096)
    np.testing.assert_array_equal(native.gather_rows(src, big_idx, n_threads=4),
                                  src[big_idx])

    px = rng.integers(0, 256, size=(64, 8), dtype=np.uint8)
    np.testing.assert_allclose(native.normalize_u8(px),
                               px.astype(np.float32) / 255.0, rtol=1e-6)
    np.testing.assert_allclose(native.normalize_u8(px, -1.0, 1.0),
                               px.astype(np.float32) * (2 / 255) - 1.0,
                               rtol=1e-5, atol=1e-6)

    labs = rng.integers(0, 9, 100)
    np.testing.assert_array_equal(native.one_hot(labs, 9),
                                  np.eye(9, dtype=np.float32)[labs])
    with pytest.raises(ValueError):
        native.one_hot([9], 9)


def test_csv_trailing_delimiter_falls_back():
    # '1,2,\n' has an empty trailing field: the Python csv module keeps it,
    # so the native fast path must defer rather than silently drop it
    assert native.csv_parse(b"1,2,\n3,4,\n") is None
    # exact float64 parity with Python float() on a precision-heavy value
    m = native.csv_parse(b"16777217,0.1\n")
    assert m is not None and m.dtype == np.float64
    assert m[0, 0] == float("16777217") and m[0, 1] == float("0.1")


def test_csv_internal_whitespace_falls_back():
    # "1 2" is a string field to the Python parser; native must defer
    assert native.csv_parse(b"1 2\n3 4\n") is None


def test_csv_strict_grammar_defers_nonportable_spellings():
    # strtod would accept all of these, but they are either locale-dependent,
    # spelled differently by Python float(), or rejected by it — the native
    # path must defer to the Python parser (which handles them consistently)
    assert native.csv_parse(b"0x10,2\n") is None          # hex float
    assert native.csv_parse(b"0x1p3,2\n") is None         # hex exponent
    assert native.csv_parse(b"inf,2\n") is None           # float('inf') ok, but defer
    assert native.csv_parse(b"infinity,2\n") is None
    assert native.csv_parse(b"nan,2\n") is None
    assert native.csv_parse(b"NAN(chars),2\n") is None    # strtod-only spelling
    assert native.csv_parse(b"1_0,2\n") is None           # float('1_0')==10.0
    assert native.csv_parse(b" 1.5,2\n") is None          # leading space: strip()ed by Python
    # strict decimal forms all still take the fast path, exact parity
    m = native.csv_parse(b"1.,.5,-3e-2,+4E+1,16777217\n")
    assert m is not None
    assert m.tolist() == [[float("1."), float(".5"), float("-3e-2"),
                           float("+4E+1"), float("16777217")]]


def test_csv_int_looking_fields_take_fast_path_as_floats():
    # documented all-float contract: the Python fallback's _coerce also
    # returns float for int-looking fields, so the paths agree
    from deeplearning4j_tpu.datasets.records.reader import _coerce
    m = native.csv_parse(b"1,2,3\n")
    assert m is not None and m.tolist() == [[1.0, 2.0, 3.0]]
    assert [_coerce(v) for v in "1,2,3".split(",")] == [1.0, 2.0, 3.0]
