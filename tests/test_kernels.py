"""Pallas kernel tests (interpret mode on CPU; the same kernels compile via
Mosaic on TPU). Parity oracle: parallel/ring_attention.attention_reference."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.kernels import flash_attention
from deeplearning4j_tpu.parallel.ring_attention import attention_reference


def _qkv(b=2, t=64, h=2, d=16, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(dtype))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_flash_attention_multi_block_asymmetric():
    # Tq != Tk (cross-attention shape) and several blocks each way
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 48, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 96, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 96, 2, 16)).astype(np.float32))
    out = flash_attention(q, k, v, block_q=16, block_k=32)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_flash_attention_gradients_match_reference():
    q, k, v = _qkv(t=32, d=8, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=16,
                                       block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fused_backward_multi_block_asymmetric(causal):
    """The fused Pallas backward (dq/dk/dv kernels, no [Tq,Tk] materialized)
    must match the reference VJP across several blocks each way and Tq != Tk."""
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.normal(size=(2, 64, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 96, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 96, 2, 16)).astype(np.float32))
    if causal:
        k, v = k[:, :64], v[:, :64]  # causal requires Tq == Tk semantics
    ct = jnp.asarray(rng.normal(size=(2, 64, 2, 16)).astype(np.float32))

    def run(fn):
        out, vjp = jax.vjp(lambda a, b, c: fn(a, b, c), q, k, v)
        return out, vjp(ct)

    out_f, gf = run(lambda a, b, c: flash_attention(
        a, b, c, causal=causal, block_q=16, block_k=32))
    out_r, gr = run(lambda a, b, c: attention_reference(a, b, c, causal=causal))
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                               rtol=2e-5, atol=2e-6)
    for name, a, b in zip("q k v".split(), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_flash_fused_backward_bf16():
    q, k, v = _qkv(t=32, d=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss(fn, *args):
        return jnp.sum(fn(*args).astype(jnp.float32) ** 2)

    gf = jax.grad(lambda a, b, c: loss(
        lambda *t: flash_attention(*t, causal=True, block_q=16, block_k=16),
        a, b, c), argnums=(0, 1, 2))(qb, kb, vb)
    gr = jax.grad(lambda a, b, c: loss(
        lambda *t: attention_reference(*t, causal=True), a, b, c),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, dtype=np.float32),
                                   np.asarray(b), rtol=1e-1, atol=1e-1)


def test_flash_attention_ragged_seq_shrinks_block():
    # T=50 doesn't tile into 16-blocks: the block shrinks to the largest
    # divisor (10) and the kernel still runs (tiny fp reassociation diffs vs
    # the reference; the old behavior silently materialized [T,T] instead)
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(1, 50, 1, 8)).astype(np.float32))
    out = flash_attention(q, q, q, causal=True, block_q=16, block_k=16)
    ref = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=1e-6)


def test_flash_attention_fallback_on_narrow_head():
    # D=6 violates the kernel's lane contract (D % 8) in every mode ->
    # silently uses the reference path (only the default-scale rounding
    # differs: f64 Python float here vs f32 jnp.sqrt inside the reference)
    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.normal(size=(1, 32, 1, 6)).astype(np.float32))
    out = flash_attention(q, q, q, causal=True)
    ref = attention_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-7)


def test_flash_attention_bf16():
    q, k, v = _qkv(t=32, d=16, dtype=np.float32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=5e-2, atol=5e-2)


def test_self_attention_layer_pallas_path_matches():
    """SelfAttentionLayer(use_pallas=True) must produce the same network
    outputs and train the same as the XLA blockwise path."""
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    SelfAttentionLayer, RnnOutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)

    def build(use_pallas):
        # n_out=16 / n_heads=2 -> head_dim 8: satisfies the kernel's D % 8
        # guard, so the pallas path genuinely executes (not the fallback)
        conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.05))
                .list()
                .layer(SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                          block_size=8, use_pallas=use_pallas,
                                          activation="identity"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="MCXENT"))
                .set_input_type(InputType.recurrent(6))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 16))]
    a, b = build(False), build(True)

    # prove the kernel path is actually taken, not the shape fallback
    import importlib
    # the package re-exports the function under the submodule's name, so
    # attribute-style import resolves to the function; go via sys.modules
    fa_mod = importlib.import_module(
        "deeplearning4j_tpu.kernels.flash_attention")
    calls = []
    orig = fa_mod._flash_forward
    fa_mod._flash_forward = lambda *a_, **k_: (calls.append(1),
                                               orig(*a_, **k_))[1]
    try:
        out_b = np.asarray(b.output(x))
    finally:
        fa_mod._flash_forward = orig
    assert calls, "pallas kernel was never invoked — fallback took over"

    np.testing.assert_allclose(np.asarray(a.output(x)), out_b,
                               rtol=1e-5, atol=1e-6)
    for _ in range(3):
        a.fit(DataSet(x, y))
        b.fit(DataSet(x, y))
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_key_mask_matches_reference(causal):
    """VERDICT r4 #3: key masks fold into the kernel's score tiles (fwd +
    both backward kernels) — ragged/packed batches keep the fast path
    instead of branching to blockwise."""
    rng = np.random.default_rng(13)
    B, T = 2, 64
    q, k, v = _qkv(b=B, t=T, seed=13)
    mask = (rng.random((B, T)) > 0.4).astype(np.float32)
    mask[0, 16:32] = 0.0   # a fully-masked interior block (block_k=16)
    mask[:, 0] = 1.0       # every row keeps a causally-visible valid key
    mask = jnp.asarray(mask)

    out = flash_attention(q, k, v, causal=causal, key_mask=mask,
                          block_q=16, block_k=16)
    ref = attention_reference(q, k, v, causal=causal, key_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)

    gf = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=causal, key_mask=mask, block_q=16, block_k=16) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(attention_reference(
        a, b, c, causal=causal, key_mask=mask) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_flash_attention_lse_merge_matches_full():
    """flash_attention_lse partials over disjoint key shards merge (by
    log-sum-exp) into exactly the full attention — the identity the ring
    path relies on — and the merged gradient (which exercises the LSE
    cotangent's delta fold) matches too."""
    import importlib
    fa = importlib.import_module(
        "deeplearning4j_tpu.kernels.flash_attention")
    q, k, v = _qkv(t=64, seed=17)
    tw = lambda w: w.transpose(0, 2, 1)[..., None]

    def merged(q, k, v):
        o1, l1 = fa.flash_attention_lse(q, k[:, :32], v[:, :32],
                                        block_q=16, block_k=16)
        o2, l2 = fa.flash_attention_lse(q, k[:, 32:], v[:, 32:],
                                        block_q=16, block_k=16)
        m = jnp.maximum(l1, l2)
        w1, w2 = jnp.exp(l1 - m), jnp.exp(l2 - m)
        return (o1 * tw(w1) + o2 * tw(w2)) / tw(w1 + w2)

    full = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(merged(q, k, v)), np.asarray(full),
                               rtol=2e-5, atol=2e-6)
    gm = jax.grad(lambda a, b, c: jnp.sum(merged(a, b, c) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(attention_reference(a, b, c) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gm, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_flash_attention_lse_global_offsets_causal():
    """Dynamic q/k position offsets drive the causal mask in-kernel (the
    ring path's per-shard global positions) — including traced offsets
    under jit."""
    import importlib
    fa = importlib.import_module(
        "deeplearning4j_tpu.kernels.flash_attention")
    q, k, v = _qkv(t=32, seed=19)
    # queries at global 32..63 vs keys at global 0..31: all keys visible
    out, _ = fa.flash_attention_lse(q, k, v, causal=True, q_offset=32,
                                    k_offset=0, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=2e-5, atol=2e-6)
    # keys at global 32..63 vs queries at 0..31: strictly future — every
    # row degenerates (uniform over the computed blocks); just check the
    # reverse diagonal: same offsets on both sides == plain causal
    out2, _ = jax.jit(lambda off: fa.flash_attention_lse(
        q, k, v, causal=True, q_offset=off, k_offset=off,
        block_q=16, block_k=16))(jnp.int32(96))
    np.testing.assert_allclose(
        np.asarray(out2),
        np.asarray(attention_reference(q, k, v, causal=True)),
        rtol=2e-5, atol=2e-6)


def test_self_attention_layer_pallas_masked_path():
    """A masked SelfAttentionLayer(use_pallas=True) must now run the Pallas
    kernel (not branch to blockwise) and match the blockwise path's outputs
    and training trajectory."""
    import importlib
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    SelfAttentionLayer, RnnOutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)

    def build(use_pallas):
        conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.05))
                .list()
                .layer(SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                          block_size=8, use_pallas=use_pallas,
                                          activation="identity"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="MCXENT"))
                .set_input_type(InputType.recurrent(6))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 16))]
    mask = np.ones((2, 16), np.float32)
    mask[0, 10:] = 0.0   # ragged batch: row 0 is a length-10 sequence
    a, b = build(False), build(True)

    fa_mod = importlib.import_module(
        "deeplearning4j_tpu.kernels.flash_attention")
    calls = []
    orig = fa_mod._flash_forward
    fa_mod._flash_forward = lambda *a_, **k_: (calls.append(1),
                                               orig(*a_, **k_))[1]
    try:
        for _ in range(3):
            a.fit(DataSet(x, y, features_mask=mask, labels_mask=mask))
            b.fit(DataSet(x, y, features_mask=mask, labels_mask=mask))
    finally:
        fa_mod._flash_forward = orig
    assert calls, "masked pallas path fell back — kernel never invoked"
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-4, atol=1e-5)


def test_self_attention_layer_attention_dropout():
    """attention_dropout drops the attention output at train time only; a
    zero rate leaves the training trajectory bit-compatible with a config
    that doesn't mention it."""
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    SelfAttentionLayer, RnnOutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)

    def build(**extra):
        conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.05))
                .list()
                .layer(SelfAttentionLayer(n_out=16, n_heads=2,
                                          activation="identity", **extra))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="MCXENT"))
                .set_input_type(InputType.recurrent(6))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 8, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 8))]

    plain, zero, dropped = (build(), build(attention_dropout=0.0),
                            build(attention_dropout=0.5))
    # eval-mode output is unaffected by the dropout rate
    np.testing.assert_allclose(np.asarray(plain.output(x)),
                               np.asarray(dropped.output(x)),
                               rtol=1e-6, atol=1e-7)
    for net in (plain, zero, dropped):
        net.fit(DataSet(x, y))
    # rate 0.0 consumes no rng and trains identically to the plain config
    np.testing.assert_allclose(plain.get_flat_params(),
                               zero.get_flat_params(), rtol=0, atol=0)
    # rate 0.5 actually perturbs training
    assert not np.allclose(plain.get_flat_params(), dropped.get_flat_params(),
                           rtol=1e-4, atol=1e-5)
