"""Fleet-scope observability: W3C traceparent propagation, collision-free
random hex ids, batch span links, histogram exemplars, and the fleet
aggregation plane (telemetry/propagation.py + telemetry/fleet.py).

The acceptance test at the bottom drives the whole ISSUE-7 loop live:
client post_json -> /predict -> batcher dispatch is ONE trace across client
and server spans with the request linked to its batch; /fleet/trace over two
live servers renders two pid lanes; a firing alert's payload carries an
exemplar trace_id whose spans and /logs records are retrievable.
"""
import multiprocessing
import random

import numpy as np
import pytest

from deeplearning4j_tpu.telemetry import (AlertRule, FleetCollector,
                                          FleetServer, MetricsRegistry,
                                          SpanContext, Tracer, extract,
                                          extract_message,
                                          format_traceparent, inject,
                                          inject_message, parse_traceparent)
from deeplearning4j_tpu.telemetry.trace import (get_tracer, new_span_id,
                                                new_trace_id)
from deeplearning4j_tpu.util.http import get_json, post_json
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


class StubModel:
    def output(self, x):
        return np.asarray(x) * 2.0


# ------------------------------------------------------------- traceparent

def test_traceparent_roundtrip_and_w3c_shape():
    t = Tracer(enabled=True)
    with t.span("op") as s:
        assert len(s.trace_id) == 32 and len(s.span_id) == 16
        int(s.trace_id, 16), int(s.span_id, 16)      # valid hex
        header = format_traceparent(s)
        assert header == f"00-{s.trace_id}-{s.span_id}-01"
        ctx = parse_traceparent(header)
        assert ctx == SpanContext(s.trace_id, s.span_id)
        # a span parented on the extracted context continues the SAME trace
        child = Tracer(enabled=True).start_span("remote_child", parent=ctx)
        assert child.trace_id == s.trace_id
        assert child.parent_id == s.span_id
        child.end()


def test_traceparent_malformed_inputs_degrade_to_no_parent():
    """Property sweep: every malformation — truncations at any byte, wrong
    version, flipped separators, non-hex, all-zero ids, non-strings — parses
    to None, never raises."""
    good = f"00-{'ab' * 16}-{'cd' * 8}-01"
    assert parse_traceparent(good) is not None
    # truncation at EVERY length short of a full header
    for n in range(len(good)):
        assert parse_traceparent(good[:n]) is None, n
    # wrong version bytes
    for version in ("01", "ff", "0", "000", "zz"):
        assert parse_traceparent(
            f"{version}-{'ab' * 16}-{'cd' * 8}-01") is None
    # all-zero trace/span ids are explicitly invalid per W3C
    assert parse_traceparent(f"00-{'0' * 32}-{'cd' * 8}-01") is None
    assert parse_traceparent(f"00-{'ab' * 16}-{'0' * 16}-01") is None
    # random single-character corruptions that break the grammar
    rng = random.Random(0)
    corrupted = 0
    for _ in range(300):
        i = rng.randrange(len(good))
        c = rng.choice("ghijkxyz!-_ GHXYZ")
        mutated = good[:i] + c + good[i + 1:]
        ctx = parse_traceparent(mutated)         # must never raise
        if ctx is None:
            corrupted += 1
        else:
            # a hex-for-hex swap can stay valid; it must still be w3c-shaped
            assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert corrupted > 200           # the sweep mostly produced real garbage
    # non-string junk
    for junk in (None, 7, b"00-" + b"ab" * 16, ["00"], {"v": 1}):
        assert parse_traceparent(junk) is None


def test_extract_is_case_insensitive_and_never_raises():
    ctx = SpanContext("ab" * 16, "cd" * 8)
    hdr = format_traceparent(ctx)
    assert extract({"traceparent": hdr}) == ctx
    assert extract({"TraceParent": hdr}) == ctx
    assert extract({}) is None
    assert extract(None) is None
    assert extract({"traceparent": "garbage"}) is None


def test_inject_without_active_span_adds_nothing():
    headers = {}
    assert inject(headers) == {} and headers == {}
    t = Tracer(enabled=True)
    with t.span("op") as s:
        inject(headers)
        assert parse_traceparent(headers["traceparent"]).trace_id == s.trace_id


def test_inject_never_overwrites_a_relayed_traceparent():
    """A relay forwarding an explicit caller context inside its own span
    must not sever the originating trace (same rule as inject_message)."""
    original = f"00-{'a' * 32}-{'b' * 16}-01"
    t = Tracer(enabled=True)
    with t.span("relay"):
        headers = inject({"traceparent": original})
        assert headers["traceparent"] == original
        mixed = inject({"Traceparent": original})   # case-insensitive lookup
        assert "traceparent" not in mixed and mixed["Traceparent"] == original


def test_message_injection_preserves_existing_context():
    t = Tracer(enabled=True)
    msg = {"payload": 1}
    assert inject_message(msg) is msg            # no active span: untouched
    with t.span("producer") as s:
        out = inject_message(msg)
        assert out is not msg and "traceparent" not in msg
        assert extract_message(out).trace_id == s.trace_id
        # a relay re-publishing a message must NOT stamp its own context
        # over the originating request's
        relayed = inject_message(out)
        assert extract_message(relayed).span_id == s.span_id


# ------------------------------------------------------------- id hygiene

def _child_ids(q):
    # an adversarially-seeded random module must not influence the ids:
    # os.urandom reads the kernel CSPRNG, unaffected by fork or seeding
    random.seed(1234)
    q.put([new_trace_id() for _ in range(200)]
          + [new_span_id() for _ in range(200)])


def test_ids_never_collide_across_forked_processes():
    """The old `_next_id` was a process-local counter restarting at 1 — two
    hosts' traces collided id-for-id. Random hex ids from the kernel CSPRNG
    must be disjoint across forked children even with random reseeded."""
    ctx = multiprocessing.get_context("fork")
    queues, procs = [], []
    for _ in range(2):
        q = ctx.Queue()
        p = ctx.Process(target=_child_ids, args=(q,))
        p.start()
        queues.append(q)
        procs.append(p)
    sets = [set(q.get(timeout=30)) for q in queues]
    for p in procs:
        p.join(30)
    random.seed(1234)
    parent = set([new_trace_id() for _ in range(200)]
                 + [new_span_id() for _ in range(200)])
    assert sets[0].isdisjoint(sets[1])
    assert parent.isdisjoint(sets[0] | sets[1])
    assert all(len(s) == 400 for s in sets + [parent])   # none within either


# ------------------------------------------------------------- span links

def test_batch_links_export_as_flow_events_with_integer_lanes():
    t = Tracer(enabled=True)
    with t.span("request_a") as a:
        pass
    with t.span("request_b") as b:
        pass
    batch = t.start_span("batch", n_requests=2)
    batch.add_link(a).add_link(b).add_link(None)     # None ctx: ignored
    batch.end()
    assert batch.to_dict()["links"] == [
        {"trace_id": a.trace_id, "span_id": a.span_id},
        {"trace_id": b.trace_id, "span_id": b.span_id}]
    ct = t.to_chrome_trace()
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert all(isinstance(e["tid"], int) for e in xs)   # hex ids -> int lanes
    assert len({e["tid"] for e in xs}) == 3             # three traces, 3 lanes
    flows = [e for e in ct["traceEvents"] if e.get("cat") == "link"]
    # two links -> two s/f pairs
    assert sorted(e["ph"] for e in flows) == ["f", "f", "s", "s"]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    assert all(len(pair) == 2 for pair in by_id.values())


# -------------------------------------------------------------- exemplars

def test_exemplar_reservoir_bounded_under_10k_observations():
    reg = MetricsRegistry()
    h = reg.histogram("latency_ms")
    for i in range(10_000):
        h.observe(float(i % 97), trace_id=f"trace-{i}",
                  route=f"r{i % 3}")                   # 3 label-sets
    for r in range(3):
        ex = h.exemplars(route=f"r{r}")
        assert len(ex) == h.exemplar_cap == 10
        # latest-wins: the newest observations for that label-set survive
        tail = [e["trace_id"] for e in ex]
        expect = [f"trace-{i}" for i in range(10_000)
                  if i % 3 == r][-10:]
        assert tail == expect
    assert len(h.exemplars()) == 30                    # merged, still bounded
    # observations without any trace context record NO exemplar
    h2 = reg.histogram("plain_ms")
    h2.observe(5.0)
    assert h2.exemplars() == []
    snap = reg.snapshot()
    assert len(snap["latency_ms"]["exemplars"]) == 30


def test_exemplars_render_as_openmetrics_and_auto_capture_current_span():
    reg = MetricsRegistry()
    h = reg.histogram("latency_ms", buckets=(1.0, 10.0))
    t = Tracer(enabled=True)
    with t.span("slow_request") as s:
        h.observe(7.5)                  # trace id auto-captured from context
    text = reg.to_prometheus()
    line = next(l for l in text.splitlines()
                if l.startswith('latency_ms_bucket{le="10"}'))
    assert f'# {{trace_id="{s.trace_id}"}} 7.5' in line
    # the 1.0 bucket saw nothing: no exemplar suffix
    low = next(l for l in text.splitlines()
               if l.startswith('latency_ms_bucket{le="1"}'))
    assert "#" not in low


def test_histogram_threshold_alert_event_carries_exemplars():
    from deeplearning4j_tpu.telemetry.alerts import AlertEngine
    reg = MetricsRegistry()
    h = reg.histogram("latency_ms")
    h.observe(5000.0, trace_id="slow-trace")
    engine = AlertEngine(registry=reg, interval_s=0, rules=[
        AlertRule("lat", metric="latency_ms", percentile=0.99,
                  threshold=100.0)])
    events = engine.evaluate()
    assert len(events) == 1 and events[0]["state"] == "firing"
    assert [e["trace_id"] for e in events[0]["exemplars"]] == ["slow-trace"]


# ------------------------------------------------------- fleet aggregation

def test_fleet_collector_manual_clock_two_servers_one_dead(manual_clock):
    """Two live in-process servers + one dead peer, ManualClock-driven
    re-poll gating — zero real sleeps."""
    from deeplearning4j_tpu.serving import ServingServer
    s1 = ServingServer(StubModel(), port=0).start()
    s2 = ServingServer(StubModel(), port=0).start()
    try:
        post_json(s1.url + "/predict", {"data": [[1.0, 2.0]]}, timeout=30)
        dead = "http://127.0.0.1:9"      # discard port: refused instantly
        fc = FleetCollector([s1.url, s2.url, dead],
                            names=["a", "b", "dead"], interval_s=30.0,
                            timeout_s=2.0)
        assert fc.maybe_poll() is True
        assert fc.maybe_poll() is False          # cached: inside interval
        manual_clock.advance(31.0)
        assert fc.maybe_poll() is True           # stale by the manual clock
        assert fc.polls == 2

        m = fc.metrics()
        assert m["instances_up"] == 2 and m["instances_down"] == 1
        assert m["totals"]["requests"] == 1      # summed over up instances
        assert "error" in m["instances"]["dead"]

        h = fc.healthz()
        # dead peer is DEGRADED — visible but never a fleet-level failure
        assert h["status"] == "degraded"
        assert h["components"]["dead"]["status"] == "degraded"
        assert h["components"]["a"]["status"] == "healthy"

        tr = fc.trace()
        lanes = {e["pid"] for e in tr["traceEvents"]}
        assert lanes == {0, 1}                   # one lane per LIVE host
        names = {e["args"]["name"] for e in tr["traceEvents"]
                 if e["ph"] == "M"}
        assert names == {"a", "b"}

        text = fc.prometheus()
        assert 'instance="a"' in text and 'instance="b"' in text
        assert "fleet_instances_up 2" in text
        assert "fleet_instances_down 1" in text

        al = fc.alerts()
        assert set(al["instances"]) == {"a", "b", "dead"}
        assert all(r["instance"] in ("a", "b") for r in al["rules"])
    finally:
        s1.stop()
        s2.stop()


def test_fleet_collector_rejects_misconfigured_names():
    with pytest.raises(ValueError):
        FleetCollector(["http://x:1", "http://y:1"], names=["one"])
    with pytest.raises(ValueError):
        FleetCollector(["http://x:1", "http://y:1"], names=["same", "same"])


def test_one_failing_endpoint_does_not_mark_a_live_peer_down(monkeypatch):
    """A peer serving /metrics + /healthz but not /trace (404, or one
    timed-out GET) must stay `up` with its fetched data intact — only a
    peer answering NOTHING is down."""
    import deeplearning4j_tpu.telemetry.fleet as fleet_mod

    def fake_get_json(url, timeout=None, with_status=False):
        if url.endswith("/trace"):
            raise OSError("HTTP Error 404: Not Found")
        if with_status:
            return 200, {"status": "ok"}
        if url.endswith("format=prometheus"):
            return "# HELP requests r\n# TYPE requests counter\n" \
                   "requests_total 3\n# EOF\n"
        if url.endswith("/alerts"):
            return {"firing": 0, "rules": []}
        return {"requests": 3}

    monkeypatch.setattr(fleet_mod, "get_json", fake_get_json)
    fc = FleetCollector(["http://peer:1"], names=["p"])
    state = fc.poll_once()["p"]
    assert state["status"] == "up"
    assert state["metrics"] == {"requests": 3}
    assert "trace" in state["errors"] and len(state["errors"]) == 1

    m = fc.metrics()
    assert m["instances_up"] == 1 and m["totals"]["requests"] == 3
    assert fc.healthz()["components"]["p"]["status"] == "healthy"
    assert fc.trace()["traceEvents"] == []       # no lane, but no failure
    assert 'instance="p"' in fc.prometheus()


def test_relabel_handles_brace_inside_quoted_label_value():
    """'}' inside a quoted label value is legal exposition text; the sample
    must still get the instance label (an unlabeled duplicate across two
    peers would break the merged OpenMetrics doc)."""
    from deeplearning4j_tpu.telemetry.fleet import _relabel_prometheus
    out = _relabel_prometheus(
        'hits_total{route="/a}b",code="200"} 7\n'
        'esc_total{v="q\\"}x"} 1\n'
        "plain_total 2\n", "h0")
    assert out[0] == 'hits_total{instance="h0",route="/a}b",code="200"} 7'
    assert out[1] == 'esc_total{instance="h0",v="q\\"}x"} 1'
    assert out[2] == 'plain_total{instance="h0"} 2'


# ------------------------------------------------------ streaming context

def test_broker_messages_carry_trace_context():
    from deeplearning4j_tpu.streaming import BrokerClient, MessageBroker
    broker = MessageBroker(port=0, registry=MetricsRegistry()).start()
    client = BrokerClient(port=broker.port)
    try:
        t = Tracer(enabled=True)
        with t.span("producer") as s:
            client.publish("topic", {"kind": "registry_change", "v": 2})
        got = client.poll("topic", timeout=5)
        assert got["kind"] == "registry_change"
        ctx = extract_message(got)
        assert ctx is not None and ctx.trace_id == s.trace_id
        # un-traced publishes stay untouched
        client.publish("topic", {"kind": "plain"})
        assert extract_message(client.poll("topic", timeout=5)) is None
    finally:
        client.close()
        broker.stop()


def test_serve_route_links_inputs_and_propagates_context():
    from deeplearning4j_tpu.streaming import (NDArrayMessage, QueueSink,
                                              QueueSource, ServeRoute)
    from deeplearning4j_tpu.telemetry.trace import set_tracer
    old = get_tracer()
    tracer = set_tracer(Tracer(enabled=True))
    try:
        src, sink = QueueSource(), QueueSink()
        with tracer.span("origin") as origin:
            header = format_traceparent(origin)
        src.put(NDArrayMessage(np.ones((1, 4), np.float32),
                               traceparent=header))
        route = ServeRoute(StubModel(), src, sink, poll_timeout=0.01).start()
        try:
            for _ in range(500):
                if sink.messages:
                    break
                import time
                time.sleep(0.01)
            assert sink.messages, "route produced nothing"
        finally:
            route.stop()
        # the prediction message still carries the ORIGIN's context
        assert sink.messages[0].traceparent == header
        assert sink.messages[0].trace_context().trace_id == origin.trace_id
        dispatch = [s for s in tracer.finished_spans()
                    if s.name == "route_dispatch"]
        assert dispatch and dispatch[0].links[0]["trace_id"] == origin.trace_id
    finally:
        set_tracer(old)


# ------------------------------------------------------------- acceptance

def test_acceptance_fleet_trace_exemplar_logs_loop():
    """ISSUE 7 acceptance: client post_json -> /predict -> batcher dispatch
    is ONE trace_id spanning client and server spans, with the request span
    linked to its batch span; /fleet/trace over two live servers renders
    both hosts in distinct pid lanes; a firing alert's payload carries an
    exemplar trace_id whose spans and /logs records are retrievable."""
    from deeplearning4j_tpu.serving import ServingServer
    fired = []
    s1 = ServingServer(StubModel(), port=0, alert_interval_s=0,
                       alert_rules=[AlertRule(
                           "latency_always", metric="latency_ms",
                           percentile=0.5, threshold=0.0, op=">")],
                       alert_sinks=[fired.append]).start()
    s2 = ServingServer(StubModel(), port=0).start()
    fleet = FleetServer([s1.url, s2.url], names=["host-a", "host-b"],
                        interval_s=0.0).start()
    client = Tracer(enabled=True)
    try:
        with client.span("client_call") as cs:
            res = post_json(s1.url + "/predict",
                            {"data": [[1.0, 2.0, 3.0]]}, timeout=30)
            client_trace = cs.trace_id
        assert res["prediction"] == [[2.0, 4.0, 6.0]]
        post_json(s2.url + "/predict", {"data": [[1.0]]}, timeout=30)

        # --- ONE trace across client and server ---------------------------
        trace = get_json(s1.url + "/trace", timeout=30)
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        mine = [e for e in spans
                if e["args"].get("trace_id") == client_trace]
        names = {e["name"] for e in mine}
        assert {"http /predict", "predict", "admission"} <= names, names
        # the request span links to the exact batch that served it
        admission = next(e for e in mine if e["name"] == "admission")
        batch = next(e for e in spans if e["name"] == "batch")
        assert admission["args"]["batch_span_id"] == batch["args"]["span_id"]
        assert {"trace_id": client_trace,
                "span_id": admission["args"]["span_id"]} not in \
            [{"trace_id": batch["args"]["trace_id"],
              "span_id": batch["args"]["span_id"]}]  # distinct traces
        flows = [e for e in trace["traceEvents"] if e.get("cat") == "link"]
        assert flows, "request<->batch links must export as flow events"

        # --- firing alert carries a retrievable exemplar ------------------
        s1.alerts.evaluate()
        firing = [ev for ev in fired if ev["state"] == "firing"]
        assert firing, fired
        exemplars = firing[0]["exemplars"]
        assert exemplars and exemplars[-1]["trace_id"] == client_trace
        ex_trace = exemplars[-1]["trace_id"]
        # exemplar -> spans
        assert any(e["args"].get("trace_id") == ex_trace for e in spans)
        # exemplar -> correlated /logs records (three-click loop closes)
        logs = get_json(s1.url + f"/logs?trace_id={ex_trace}", timeout=30)
        assert logs["records"] and \
            logs["records"][-1]["message"] == "predict_ok"
        # the exemplar also rides the prometheus exposition
        text = get_json(s1.url + "/metrics?format=prometheus", timeout=30)
        assert f'trace_id="{ex_trace}"' in text

        # --- fleet plane over two live hosts ------------------------------
        ftrace = get_json(fleet.url + "/fleet/trace", timeout=30)
        lanes = {e["pid"] for e in ftrace["traceEvents"]}
        assert lanes == {0, 1}
        lane_names = {e["args"]["name"] for e in ftrace["traceEvents"]
                      if e["ph"] == "M"}
        assert lane_names == {"host-a", "host-b"}
        # the client trace is visible in the fleet-merged view too
        assert any(e.get("args", {}).get("trace_id") == client_trace
                   for e in ftrace["traceEvents"])
        status, fh = get_json(fleet.url + "/fleet/healthz", timeout=30,
                              with_status=True)
        assert status == 200 and fh["status"] == "healthy"
        fm = get_json(fleet.url + "/fleet/metrics", timeout=30)
        assert fm["totals"]["requests"] == 2
        assert set(fm["instances"]) == {"host-a", "host-b"}
        fa = get_json(fleet.url + "/fleet/alerts", timeout=30)
        assert any(r["state"] == "firing" and r["instance"] == "host-a"
                   for r in fa["rules"])
        ftext = get_json(fleet.url + "/fleet/metrics?format=prometheus",
                         timeout=30)
        assert 'instance="host-a"' in ftext and 'instance="host-b"' in ftext
    finally:
        fleet.stop()
        s1.stop()
        s2.stop()


def test_smoke_fleet_tool():
    """Fast variant of tools/smoke_fleet.py: the whole propagation ->
    exemplar -> fleet loop in one run."""
    import tools.smoke_fleet as smoke
    out = smoke.run(n_requests=6)
    assert out["fleet_instances_up"] == 2
    assert out["fleet_lanes"] == [0, 1]
    assert out["span_link_flows"] > 0
    assert out["exemplar_log_records"] > 0
