"""Tests for clustering, t-SNE, dataset fetchers, concurrency utils —
mirroring the reference's deeplearning4j-core test suites (KMeansTest,
KDTreeTest, VPTreeTest, TsneTest, dataset iterator tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (KMeansClustering, KDTree, VPTree,
                                           SpTree, Point)
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne
from deeplearning4j_tpu.datasets.fetchers.standard import (
    IrisDataSetIterator, CifarDataSetIterator, LFWDataSetIterator,
    CurvesDataSetIterator)
from deeplearning4j_tpu.util.concurrency import (MagicQueue, AsyncIterator,
                                                 ConcurrentHashSet)


def _blobs(n_per=40, seed=0):
    rng = np.random.default_rng(seed)
    cs = np.array([[0, 0], [8, 8], [0, 8]])
    x = np.concatenate([c + rng.normal(size=(n_per, 2)) for c in cs])
    y = np.repeat(np.arange(3), n_per)
    return x.astype(np.float32), y


# ------------------------------------------------------------- clustering

def test_kmeans_recovers_blobs():
    x, y = _blobs()
    km = KMeansClustering.setup(3, max_iterations=50, seed=1)
    cs = km.apply_to(x)
    assign = cs.assignments
    # purity: every true cluster maps dominantly to one k-means cluster
    purity = 0
    for c in range(3):
        labels, counts = np.unique(assign[y == c], return_counts=True)
        purity += counts.max()
    assert purity / len(x) > 0.95
    # nearest_cluster works
    assert cs.nearest_cluster([8, 8]).id == assign[y == 1][0]


def test_kmeans_point_objects():
    x, _ = _blobs(10)
    pts = [Point(row, point_id=i) for i, row in enumerate(x)]
    cs = KMeansClustering(3, seed=0).apply_to(pts)
    assert sum(len(c.points) for c in cs.get_clusters()) == len(pts)


def test_kdtree_knn_matches_bruteforce():
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(200, 5))
    tree = KDTree(points=pts)
    q = rng.normal(size=5)
    d = np.linalg.norm(pts - q, axis=1)
    expect = set(np.argsort(d)[:7])
    got = {idx for _, _, idx in tree.knn(q, 7)}
    assert got == expect
    nn = tree.nn(q)
    assert nn[2] == int(np.argmin(d))


def test_kdtree_insert():
    tree = KDTree(dims=2)
    for i, p in enumerate([[0, 0], [1, 1], [2, 2], [0.1, 0.1]]):
        tree.insert(p, i)
    assert tree.size == 4
    assert tree.nn([0.05, 0.05])[2] in (0, 3)


def test_vptree_knn_matches_bruteforce():
    rng = np.random.default_rng(3)
    pts = rng.normal(size=(150, 8))
    tree = VPTree(pts, seed=4)
    q = rng.normal(size=8)
    d = np.linalg.norm(pts - q, axis=1)
    expect = list(np.argsort(d)[:5])
    idxs, dists = tree.search(q, 5)
    assert idxs == expect
    np.testing.assert_allclose(dists, np.sort(d)[:5], rtol=1e-9)


def test_sptree_mass_and_forces():
    rng = np.random.default_rng(4)
    pts = rng.normal(size=(50, 2))
    tree = SpTree(pts)
    assert tree.cum_size == 50
    np.testing.assert_allclose(tree.center_of_mass, pts.mean(0), rtol=1e-9)
    # theta=0 forces == exact O(N^2) computation
    q = pts[0]
    neg = np.zeros(2)
    z = tree.compute_non_edge_forces(q, 0.0, neg)
    diff = q[None] - pts[1:]
    qk = 1.0 / (1.0 + (diff ** 2).sum(1))
    z_exact = qk.sum()
    neg_exact = (qk[:, None] ** 2 * diff).sum(0)
    np.testing.assert_allclose(z, z_exact, rtol=1e-6)
    np.testing.assert_allclose(neg, neg_exact, rtol=1e-6)


def test_kdtree_equidistant_duplicates():
    tree = KDTree(points=[[0, 0], [1, 1], [1, 1], [2, 2]])
    res = tree.knn([0.9, 0.9], 3)  # duplicate points must not crash the sort
    assert len(res) == 3
    assert res[0][0] <= res[1][0] <= res[2][0]


def test_vptree_duplicate_heavy_no_recursion_blowup():
    pts = np.zeros((1500, 3))
    pts[:5] = np.arange(15).reshape(5, 3)
    tree = VPTree(pts, seed=1)
    idxs, dists = tree.search(np.zeros(3), 4)
    assert len(idxs) == 4
    assert dists[0] == 0.0


# ------------------------------------------------------------------ t-SNE

def test_tsne_exact_separates_blobs():
    x, y = _blobs(25, seed=5)
    ts = Tsne(perplexity=15.0, n_iter=300, seed=6)
    Y = ts.fit_transform(x)
    assert Y.shape == (75, 2)
    # cluster separation in the embedding: mean inter-centroid distance
    # exceeds mean intra-cluster spread
    cents = np.stack([Y[y == c].mean(0) for c in range(3)])
    intra = np.mean([np.linalg.norm(Y[y == c] - cents[c], axis=1).mean()
                     for c in range(3)])
    inter = np.mean([np.linalg.norm(cents[a] - cents[b])
                     for a in range(3) for b in range(a + 1, 3)])
    assert inter > 2 * intra


def test_tsne_barnes_hut_separates_blobs():
    x, y = _blobs(20, seed=7)
    ts = BarnesHutTsne(perplexity=10.0, n_iter=250, theta=0.5, seed=8)
    Y = ts.fit_transform(x)
    assert Y.shape == (60, 2)
    cents = np.stack([Y[y == c].mean(0) for c in range(3)])
    intra = np.mean([np.linalg.norm(Y[y == c] - cents[c], axis=1).mean()
                     for c in range(3)])
    inter = np.mean([np.linalg.norm(cents[a] - cents[b])
                     for a in range(3) for b in range(a + 1, 3)])
    assert inter > 1.5 * intra


# --------------------------------------------------------------- fetchers

def test_iris_iterator():
    it = IrisDataSetIterator(batch_size=50)
    ds = it.next()
    assert ds.features.shape == (50, 4)
    assert ds.labels.shape == (50, 3)
    total = 50
    while it.has_next():
        total += it.next().num_examples()
    assert total == 150


def test_cifar_iterator_trains():
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    ConvolutionLayer, SubsamplingLayer,
                                    OutputLayer, MultiLayerNetwork, Adam)
    it = CifarDataSetIterator(batch_size=32, num_examples=128)
    ds = it.next()
    assert ds.features.shape == (32, 32, 32, 3)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2)).list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8,
                                    activation="relu", convolution_mode="same"))
            .layer(SubsamplingLayer(kernel_size=(4, 4), stride=(4, 4)))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.convolutional(32, 32, 3))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=3)
    assert np.isfinite(net.score_value)


def test_lfw_and_curves_iterators():
    lfw = LFWDataSetIterator(batch_size=8, num_examples=32,
                             image_size=(16, 16), num_labels=4)
    ds = lfw.next()
    assert ds.features.shape == (8, 16, 16, 3)
    assert ds.labels.shape == (8, 4)
    curves = CurvesDataSetIterator(batch_size=16, num_examples=64)
    ds = curves.next()
    assert ds.features.shape == (16, 784)
    np.testing.assert_array_equal(ds.features, ds.labels)  # autoencoder target


# ------------------------------------------------------------ concurrency

def test_magic_queue_round_robin():
    mq = MagicQueue(3)
    for i in range(6):
        mq.add(i)
    assert mq.poll(0) == 0 and mq.poll(0) == 3
    assert mq.poll(1) == 1 and mq.poll(2) == 2
    assert mq.size() == 2


def test_magic_queue_close_unblocks_concurrent_takers():
    """close() must wake EVERY blocked taker deterministically — including
    several concurrent takers on the same worker (the old sentinel scheme
    delivered one wake per worker queue, stranding the rest)."""
    import threading
    mq = MagicQueue(2)
    results = []
    lock = threading.Lock()

    def taker(worker):
        item = mq.poll(worker, timeout=10)
        with lock:
            results.append((worker, item))

    threads = [threading.Thread(target=taker, args=(w,))
               for w in (0, 0, 1, 1)]          # two takers per worker
    for t in threads:
        t.start()
    import time
    time.sleep(0.1)                            # let all takers block
    t0 = time.monotonic()
    mq.close()
    for t in threads:
        t.join(timeout=5)
    assert time.monotonic() - t0 < 2           # woke, not timed out
    assert sorted(results) == [(0, None), (0, None), (1, None), (1, None)]


def test_magic_queue_drain_after_close():
    """Items enqueued before close() remain pollable (drain), then poll
    returns None immediately; add() after close raises."""
    import pytest as _pytest
    mq = MagicQueue(2, capacity=4)
    for i in range(4):
        mq.add(i)
    mq.close()
    assert mq.closed
    assert mq.poll(0) == 0 and mq.poll(0) == 2   # drain continues
    assert mq.poll(1) == 1
    assert mq.drain(1) == [3]                    # bulk drain path
    assert mq.poll(0) is None and mq.poll(1) is None  # immediate, no block
    with _pytest.raises(RuntimeError, match="closed"):
        mq.add(99)


def test_magic_queue_close_unblocks_full_producer():
    """A producer blocked on a full worker queue must not hang across
    close(): it wakes and raises instead of deadlocking shutdown."""
    import threading
    import time
    mq = MagicQueue(1, capacity=1)
    mq.add("fills-the-queue")
    err = []

    def producer():
        try:
            mq.add("blocks-until-close")
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.1)
    mq.close()
    t.join(timeout=5)
    assert not t.is_alive() and len(err) == 1


def test_async_iterator():
    it = AsyncIterator(iter(range(100)), buffer_size=4)
    out = list(it)
    assert out == list(range(100))
    with pytest.raises(StopIteration):  # must not hang after exhaustion
        next(it)


def test_async_iterator_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")
    it = AsyncIterator(gen())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
        next(it)


def test_concurrent_hash_set():
    s = ConcurrentHashSet()
    assert s.add("a") and not s.add("a")
    assert "a" in s and len(s) == 1
    s.remove("a")
    assert len(s) == 0


def test_kmeans_all_identical_points():
    """Regression: k-means++ D^2 sampling degenerates to all-zero probabilities
    when every point coincides (ADVICE.md round 1, low)."""
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
    x = np.ones((12, 3), np.float32)
    cs = KMeansClustering(k=3, seed=7).fit(x)
    assert len(cs.centers) == 3
    np.testing.assert_allclose(np.asarray(cs.centers), 1.0)


# ------------------------------------------------ top-N / prediction meta

def test_evaluation_top_n_and_prediction_meta():
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    import numpy as np
    e = Evaluation(top_n=3)
    labels = np.eye(5)[[0, 1, 2, 3]]
    preds = np.array([
        [0.5, 0.2, 0.1, 0.1, 0.1],   # correct, top1
        [0.4, 0.3, 0.2, 0.05, 0.05], # wrong top1, actual=1 in top3
        [0.3, 0.3, 0.05, 0.3, 0.05], # wrong, actual=2 not in top3
        [0.1, 0.2, 0.3, 0.35, 0.05], # correct
    ])
    e.eval(labels, preds, record_meta_data=["r0", "r1", "r2", "r3"])
    assert e.accuracy() == 0.5
    assert e.top_n_accuracy() == 0.75
    errs = e.get_prediction_errors()
    assert {p.record_meta for p in errs} == {"r1", "r2"}
    assert [p.record_meta for p in e.get_predictions_by_actual_class(1)] == ["r1"]
    assert [p.predicted for p in e.get_predictions_by_predicted_class(0)] == [0, 0, 0]


def test_viterbi_denoises_sequence():
    from deeplearning4j_tpu.util.viterbi import Viterbi
    import numpy as np
    v = Viterbi(np.arange(3), meta_stability=0.95, p_correct=0.9)
    # long stable runs with one-frame noise blips -> blips smoothed out
    obs = np.array([0]*10 + [1] + [0]*10 + [2]*15 + [0] + [2]*5)
    ll, path = v.decode(obs, binary_label_matrix=False)
    expect = np.array([0]*21 + [2]*21)
    np.testing.assert_array_equal(path, expect)
    assert ll < 0
    # binary label matrix input form (reference default)
    onehot = np.eye(3)[obs]
    _, path2 = v.decode(onehot)
    np.testing.assert_array_equal(path2, path)


def test_quadtree_structure_and_forces():
    from deeplearning4j_tpu.clustering.quadtree import QuadTree
    import numpy as np
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(64, 2))
    qt = QuadTree(pts)
    assert qt.cum_size == 64
    np.testing.assert_allclose(qt.center_of_mass, pts.mean(0), atol=1e-9)
    assert qt.depth() > 1
    # Barnes-Hut force at theta=0 (exact) matches brute force
    p = pts[0]
    neg, sum_q = qt.compute_non_edge_forces(p, theta=0.0)
    diffs = p - pts[1:]
    d2 = np.sum(diffs**2, axis=1)
    q = 1.0 / (1.0 + d2)
    np.testing.assert_allclose(sum_q, q.sum(), rtol=1e-9)
    np.testing.assert_allclose(neg, ((q**2)[:, None] * diffs).sum(0), rtol=1e-9,
                               atol=1e-12, err_msg="exact BH must equal brute force")
    # approximate forces stay close
    neg_a, sum_qa = qt.compute_non_edge_forces(p, theta=0.5)
    assert abs(sum_qa - q.sum()) / q.sum() < 0.1


def _gateway_h5(tmp_path):
    """Small Keras-1.x h5 (same layout the importer reads) for gateway tests."""
    import json as _json
    import numpy as np
    from deeplearning4j_tpu.modelimport import hdf5_lite
    rng = np.random.default_rng(4)
    W1 = rng.normal(size=(4, 8), scale=0.4).astype(np.float32)
    b1 = np.zeros(8, np.float32)
    W2 = rng.normal(size=(8, 3), scale=0.4).astype(np.float32)
    b2 = np.zeros(3, np.float32)
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {
            "name": "d1", "output_dim": 8, "activation": "tanh",
            "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "d2", "output_dim": 3, "activation": "softmax"}}]}
    f = hdf5_lite.H5File()
    f.attrs["keras_version"] = np.bytes_(b"1.2.2")
    f.attrs["model_config"] = np.bytes_(_json.dumps(cfg).encode())
    f.attrs["training_config"] = np.bytes_(_json.dumps(
        {"loss": "categorical_crossentropy",
         "optimizer": {"class_name": "SGD", "config": {"lr": 0.1}}}).encode())
    f.attrs["layer_names"] = np.array([b"d1", b"d2"], dtype="S4")
    for name, W, b in (("d1", W1, b1), ("d2", W2, b2)):
        g = f.create_group(name)
        g.attrs["weight_names"] = np.array(
            [f"{name}_W".encode(), f"{name}_b".encode()], dtype="S8")
        g.create_dataset(f"{name}_W", W)
        g.create_dataset(f"{name}_b", b)
    h5p = tmp_path / "gw.h5"
    f.save(h5p)
    return h5p


def test_keras_gateway_server(tmp_path):
    """HTTP gateway serving the Keras-backend entry points (reference:
    deeplearning4j-keras Server.java + DeepLearning4jEntryPoint.fit)."""
    import json as _json
    import urllib.request
    import numpy as np
    from deeplearning4j_tpu.modelimport.gateway import KerasGatewayServer
    from deeplearning4j_tpu.streaming.serde import serialize_array

    rng = np.random.default_rng(4)
    h5p = _gateway_h5(tmp_path)
    srv = KerasGatewayServer(port=0).start()
    try:
        def post(path, data, raw=False):
            req = urllib.request.Request(srv.url + path, data=data)
            with urllib.request.urlopen(req, timeout=60) as r:
                return _json.loads(r.read())

        mid = post("/models", open(h5p, "rb").read())["model_id"]
        X = rng.normal(size=(64, 4)).astype(np.float32)
        w = rng.normal(size=(4, 3))
        Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, 1)]
        out1 = post(f"/models/{mid}/fit", _json.dumps(
            {"features": _json.loads(serialize_array(X)),
             "labels": _json.loads(serialize_array(Y)),
             "epochs": 5, "batch_size": 16}).encode())
        assert out1["epochs_fit"] == 5
        pred = post(f"/models/{mid}/predict", _json.dumps(
            {"features": _json.loads(serialize_array(X))}).encode())
        assert pred["shape"] == [64, 3]
        p = np.asarray(pred["prediction"])
        np.testing.assert_allclose(p.sum(1), 1.0, rtol=1e-4)
        with urllib.request.urlopen(srv.url + f"/models/{mid}", timeout=10) as r:
            info = _json.loads(r.read())
        assert info["n_params"] == 4*8 + 8 + 8*3 + 3
    finally:
        srv.stop()


def test_keras_gateway_per_model_locks(tmp_path):
    """A long fit on model A must not block predict on model B (per-model
    locks; one global lock only guards registry mutation)."""
    import json as _json
    import threading
    import time as _time
    import urllib.request
    import numpy as np
    from deeplearning4j_tpu.modelimport.gateway import KerasGatewayServer
    from deeplearning4j_tpu.streaming.serde import serialize_array

    h5p = _gateway_h5(tmp_path)
    srv = KerasGatewayServer(port=0).start()
    try:
        def post(path, data):
            req = urllib.request.Request(srv.url + path, data=data)
            with urllib.request.urlopen(req, timeout=120) as r:
                return _json.loads(r.read())

        h5 = open(h5p, "rb").read()
        ma = post("/models", h5)["model_id"]
        mb = post("/models", h5)["model_id"]
        rng = np.random.default_rng(0)
        X = rng.normal(size=(512, 4)).astype(np.float32)
        Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 512)]
        pred_body = _json.dumps(
            {"features": _json.loads(serialize_array(X[:4]))}).encode()
        post(f"/models/{mb}/predict", pred_body)  # warm B's compile cache

        fit_secs = [0.0]

        def fit_a():
            t0 = _time.monotonic()
            post(f"/models/{ma}/fit", _json.dumps(
                {"features": _json.loads(serialize_array(X)),
                 "labels": _json.loads(serialize_array(Y)),
                 "epochs": 40, "batch_size": 8}).encode())
            fit_secs[0] = _time.monotonic() - t0

        th = threading.Thread(target=fit_a)
        th.start()
        _time.sleep(0.2)  # let the fit take its model lock
        t0 = _time.monotonic()
        out = post(f"/models/{mb}/predict", pred_body)
        pred_sec = _time.monotonic() - t0
        th.join()
        assert out["shape"] == [4, 3]
        # with the old global lock, predict waits the whole fit out
        assert pred_sec < max(0.5, fit_secs[0] / 2), \
            f"predict ({pred_sec:.2f}s) blocked behind fit ({fit_secs[0]:.2f}s)"
    finally:
        srv.stop()


def test_time_sources():
    from deeplearning4j_tpu.util.time_source import (SystemClockTimeSource,
                                                     NTPTimeSource,
                                                     TimeSourceProvider)
    import struct, time as _time
    s = SystemClockTimeSource()
    assert abs(s.current_time_millis() - _time.time() * 1000) < 2000

    # offset arithmetic from a crafted SNTP packet: server clock 5s ahead
    t = _time.time()
    ahead = t + 5.0
    sec = int(ahead) + 2208988800
    frac = int((ahead % 1) * 2**32)
    pkt = bytearray(48)
    pkt[32:40] = struct.pack("!II", sec, frac)   # receive ts (T2)
    pkt[40:48] = struct.pack("!II", sec, frac)   # transmit ts (T3)
    off = NTPTimeSource._parse_offset_ms(bytes(pkt), t, t)
    assert 4800 < off < 5200

    # zero-egress env: construction must not raise, falls back to system time
    src = NTPTimeSource(server="192.0.2.1", timeout=0.2)  # TEST-NET, no route
    assert abs(src.current_time_millis() - _time.time() * 1000) < 5000

    TimeSourceProvider.reset()
    assert isinstance(TimeSourceProvider.get_instance(), SystemClockTimeSource)
    TimeSourceProvider.reset()


def test_network_evaluate_top_n():
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd,
                                    ListDataSetIterator)
    import numpy as np
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=5, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(40, 4)).astype(np.float32)
    Y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 40)]
    e = net.evaluate(ListDataSetIterator(DataSet(X, Y), batch_size=10), top_n=3)
    assert 0.0 <= e.accuracy() <= e.top_n_accuracy() <= 1.0


def test_magic_queue_poll_timeout_under_manual_clock():
    """GL001 regression: MagicQueue.poll's deadline reads the injected time
    source, and a frozen ManualClock must NOT turn a timed poll into an
    infinite loop of real waits — one real slice elapses, then None."""
    import time as _time
    from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                     TimeSourceProvider)
    TimeSourceProvider.set_instance(ManualClock())
    try:
        mq = MagicQueue(1)
        t0 = _time.monotonic()
        assert mq.poll(0, timeout=0.05) is None       # empty: bounded wait
        assert _time.monotonic() - t0 < 5.0           # ...not an infinite spin
        mq.add("x")
        assert mq.poll(0, timeout=0.05) == "x"        # item: no wait at all
    finally:
        TimeSourceProvider.reset()
