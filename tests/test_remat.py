"""Rematerialization (conf.remat, nn/remat.py): policy-driven
jax.checkpoint over the training forward must change MEMORY/compute
trade-offs only — never the math. Parity oracle: the identical config
without remat."""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                ConvolutionLayer, SubsamplingLayer,
                                BatchNormalization, DenseLayer, OutputLayer,
                                MultiLayerNetwork, DataSet, Adam)


def _build(remat, dropout=None):
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .remat(remat).list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8,
                                    activation="relu", padding=(1, 1),
                                    dropout=dropout))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(8, 8, 3)).build())
    return MultiLayerNetwork(conf).init()


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return DataSet(x, y)


@pytest.mark.parametrize("mode", ["convs_and_dots", "dots", "full"])
def test_remat_training_matches_no_remat(mode):
    """Every policy trains bit-compatibly with the un-checkpointed config
    (recompute re-runs the same ops): params, BN running stats, scores."""
    ds = _data()
    base, net = _build(None), _build(mode)
    assert net.conf.remat == mode  # builder threads the flag through
    for _ in range(4):
        base.fit_batch(ds)
        net.fit_batch(ds)
    np.testing.assert_allclose(base.get_flat_params(), net.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    import jax
    for sa, sb in zip(jax.tree_util.tree_leaves(base.states),
                      jax.tree_util.tree_leaves(net.states)):
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(base.score_value, net.score_value, rtol=1e-5)


def test_remat_with_dropout_rng_consistency():
    """The checkpointed forward replays with the SAME rng during the
    backward recompute — dropout masks must not diverge between the two
    passes (params would silently drift if they did)."""
    ds = _data(1)
    base, net = _build(None, dropout=0.3), _build("full", dropout=0.3)
    for _ in range(4):
        base.fit_batch(ds)
        net.fit_batch(ds)
    np.testing.assert_allclose(base.get_flat_params(), net.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_remat_graph_and_multistep():
    """ComputationGraph remat (via the graph builder global conf) + the
    scanned K-step path compose: grouped training equals per-batch."""
    from deeplearning4j_tpu import ComputationGraph, ListDataSetIterator

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(1e-2))
                .remat("convs_and_dots")
                .graph_builder()
                .add_inputs("in")
                .add_layer("c", ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                                 activation="relu",
                                                 convolution_mode="same"), "in")
                .add_layer("d", DenseLayer(n_out=16, activation="relu"), "c")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="MCXENT"), "d")
                .set_outputs("out")
                .set_input_types(InputType.convolutional(8, 8, 3)).build())
        assert conf.remat == "convs_and_dots"
        return ComputationGraph(conf).init()

    rng = np.random.default_rng(2)
    sets = []
    for _ in range(4):
        x = rng.normal(size=(8, 8, 8, 3)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        sets.append(DataSet(x, y))
    a, b = build(), build()
    for ds in sets:
        a.fit_batch(ds)
    b.fit(ListDataSetIterator(sets), steps_per_execution=4)
    import jax
    for pa, pb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=1e-5, atol=1e-6)


def test_remat_unknown_mode_fails_loudly():
    net = _build("typo_mode")
    with pytest.raises(ValueError, match="unknown remat mode"):
        net.fit_batch(_data())


def test_remat_serde_round_trip():
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    d = _build("convs_and_dots").conf.to_dict()
    assert MultiLayerConfiguration.from_dict(d).remat == "convs_and_dots"
