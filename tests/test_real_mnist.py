"""BASELINE #1 on REAL data (VERDICT r3 #4): genuine handwritten digits
through the untouched MnistDataSetIterator -> LeNet fit() -> Evaluation path.

The committed fixture (tests/fixtures/mnist_real, built by
tools/make_mnist_fixture.py) holds 1297 train / 500 test real pen-stroke
digits in the MNIST idx.gz layout, so this exercises the same fetcher parsing
(idx magic/header, gzip) the reference's MnistManager does
(reference: datasets/mnist/MnistImageFile.java, MnistDataFetcher.java).
"""
import os

import numpy as np
import pytest

import deeplearning4j_tpu.datasets.fetchers.mnist as mnist_mod
from deeplearning4j_tpu.datasets.fetchers.mnist import (
    MnistDataSetIterator, load_mnist)
from deeplearning4j_tpu.zoo.models import lenet_mnist

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "mnist_real")


@pytest.fixture(autouse=True)
def pin_fixture_dir(monkeypatch):
    """Force the committed fixture even on machines that have a full local
    MNIST copy in a higher-priority candidate dir (MNIST_DIR wins the search,
    so pointing it at the fixture makes the test deterministic)."""
    monkeypatch.setenv("MNIST_DIR", FIXTURE)
    mnist_mod._CACHE.clear()
    yield
    mnist_mod._CACHE.clear()


def test_fixture_is_real_not_synthetic():
    imgs, labels = load_mnist(train=True)
    # the synthetic fallback fabricates 60k; the committed real fixture is 1297
    assert imgs.shape == (1297, 28, 28), (
        "real-digit fixture not picked up — synthetic fallback engaged")
    assert imgs.min() >= 0.0 and imgs.max() <= 1.0
    # real digits: ink is sparse (the synthetic prototypes are dense uniform
    # noise where <0.1-valued pixels are ~10%; bilinear upsampling smears
    # strokes, so the real set sits near ~38% background here)
    assert (imgs < 0.1).mean() > 0.3
    assert sorted(np.unique(labels)) == list(range(10))


def test_lenet_reaches_95pct_on_real_heldout():
    train_it = MnistDataSetIterator(batch_size=64, train=True, seed=3)
    test_it = MnistDataSetIterator(batch_size=250, train=False, shuffle=False)
    net = lenet_mnist()
    net.init()
    net.fit(train_it, epochs=6)
    ev = net.evaluate(test_it)
    acc = ev.accuracy()
    assert acc >= 0.95, f"held-out accuracy {acc:.3f} < 0.95 on real digits"


def test_pretrained_zoo_to_labels_pipeline():
    """VERDICT r3 Missing #3: zoo -> load_pretrained() -> output() ->
    decode_predictions labels, against the committed weight fixture
    (TrainedModelHelper + ImageNetLabels mechanism, exercised end to end)."""
    from deeplearning4j_tpu.zoo import (available_pretrained,
                                        load_pretrained)
    assert "lenet_mnist_real" in available_pretrained()
    net, labels = load_pretrained("lenet_mnist_real")
    test_it = MnistDataSetIterator(batch_size=500, train=False, shuffle=False)
    ds = test_it.next()
    probs = np.asarray(net.output(ds.features))
    decoded = labels.decode_predictions(probs, top=3)
    assert len(decoded) == 500 and len(decoded[0]) == 3
    # top-1 label text must match the true digit >= 95% of the time
    truth = np.argmax(ds.labels, axis=1)
    hits = sum(d[0][0] == f"digit {t}" for d, t in zip(decoded, truth))
    assert hits / len(truth) >= 0.95, f"top-1 label accuracy {hits/500:.3f}"
    # each row's probabilities are sorted descending
    assert all(d[0][1] >= d[1][1] >= d[2][1] for d in decoded)


def test_load_pretrained_missing_name_reports_search_path():
    from deeplearning4j_tpu.zoo import load_pretrained
    with pytest.raises(FileNotFoundError, match="PRETRAINED_DIR"):
        load_pretrained("vgg16_imagenet")
