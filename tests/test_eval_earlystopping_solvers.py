"""Tests for evaluation (ROC/regression), early stopping, and second-order
solvers — mirroring the reference's EvalTest/ROCTest, EarlyStoppingTest*, and
TestOptimizers suites under deeplearning4j-core/src/test."""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd, Adam,
                                ROC, ROCMultiClass, RegressionEvaluation, DataSet,
                                ListDataSetIterator)
from deeplearning4j_tpu.nn.conf.configuration import OptimizationAlgorithm
from deeplearning4j_tpu.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer, MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition, MaxTimeIterationTerminationCondition,
    InvalidScoreIterationTerminationCondition, MaxScoreIterationTerminationCondition,
    DataSetLossCalculator, InMemoryModelSaver, LocalFileModelSaver,
    TerminationReason)


# ------------------------------------------------------------------- ROC

def test_roc_perfect_classifier():
    roc = ROC(threshold_steps=50)
    labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]], float)
    # perfectly separable probabilities
    preds = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]], float)
    roc.eval(labels, preds)
    assert roc.calculate_auc() == pytest.approx(1.0)


def test_roc_random_classifier():
    rng = np.random.default_rng(0)
    n = 4000
    lab = rng.integers(0, 2, n)
    labels = np.eye(2)[lab]
    p1 = rng.random(n)                      # scores independent of label
    preds = np.stack([1 - p1, p1], axis=1)
    roc = ROC(threshold_steps=100)
    roc.eval(labels, preds)
    assert roc.calculate_auc() == pytest.approx(0.5, abs=0.05)


def test_roc_multiclass():
    rng = np.random.default_rng(1)
    n, c = 300, 3
    lab = rng.integers(0, c, n)
    labels = np.eye(c)[lab]
    logits = labels * 3 + rng.normal(size=(n, c))
    preds = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    m = ROCMultiClass(threshold_steps=60)
    m.eval(labels, preds)
    assert m.calculate_average_auc() > 0.9
    assert 0 <= m.calculate_auc(0) <= 1


def test_regression_evaluation():
    rng = np.random.default_rng(2)
    y = rng.normal(size=(200, 2))
    pred = y + 0.1 * rng.normal(size=(200, 2))
    e = RegressionEvaluation(n_columns=2)
    e.eval(y, pred)
    assert e.mean_squared_error(0) == pytest.approx(0.01, rel=0.5)
    assert e.average_r_squared() > 0.9
    assert e.pearson_correlation(1) > 0.9
    assert "MSE" in e.stats()


def test_regression_evaluation_merge():
    rng = np.random.default_rng(3)
    y1, y2 = rng.normal(size=(50, 1)), rng.normal(size=(50, 1))
    e1, e2 = RegressionEvaluation(1), RegressionEvaluation(1)
    e1.eval(y1, y1)
    e2.eval(y2, y2)
    e1.merge(e2)
    assert e1.mean_squared_error(0) == pytest.approx(0.0, abs=1e-12)


def test_roc_single_column_labels_vs_two_column_predictions():
    # 1-col {0,1} labels with 2-col softmax predictions must read P(class 1)
    roc = ROC(threshold_steps=50)
    labels = np.array([[1], [1], [0], [0]], float)
    preds = np.array([[0.1, 0.9], [0.2, 0.8], [0.9, 0.1], [0.8, 0.2]], float)
    roc.eval(labels, preds)
    assert roc.calculate_auc() == pytest.approx(1.0)


def test_roc_multiclass_2d_mask():
    labels = np.eye(3)[[0, 1, 2, 0]]
    preds = np.eye(3)[[0, 1, 2, 1]] * 0.8 + 0.1
    m_all = ROCMultiClass(50)
    m_all.eval(labels, preds)
    m_masked = ROCMultiClass(50)
    m_masked.eval(labels, preds, mask=np.array([1, 1, 1, 0]))  # drop the error row
    assert m_masked.calculate_average_auc() >= m_all.calculate_average_auc()
    assert m_masked.calculate_average_auc() == pytest.approx(1.0)


# ---------------------------------------------------------- early stopping

def _toy_net(lr=0.1, algo=None):
    b = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(lr)))
    if algo:
        b = b.optimization_algo(algo)
    conf = (b.list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    y = np.eye(2)[(x.sum(1) > 0).astype(int)]
    return ListDataSetIterator(DataSet(x, y), batch_size=16)


def test_early_stopping_max_epochs():
    net = _toy_net()
    it = _toy_data()
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .score_calculator(DataSetLossCalculator(_toy_data(seed=1)))
           .model_saver(InMemoryModelSaver())
           .build())
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION
    assert result.total_epochs == 3
    assert result.get_best_model() is not None
    assert len(result.score_vs_epoch) == 3


def test_early_stopping_score_improvement():
    net = _toy_net(lr=0.0)  # no learning -> no improvement -> stops early
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(
               MaxEpochsTerminationCondition(50),
               ScoreImprovementEpochTerminationCondition(2))
           .score_calculator(DataSetLossCalculator(_toy_data(seed=1)))
           .build())
    result = EarlyStoppingTrainer(cfg, net, _toy_data()).fit()
    assert result.total_epochs < 50


def test_early_stopping_invalid_score():
    net = _toy_net(lr=1e9)  # diverges to nan/inf quickly
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(20))
           .iteration_termination_conditions(
               InvalidScoreIterationTerminationCondition(),
               MaxScoreIterationTerminationCondition(1e7))
           .score_calculator(DataSetLossCalculator(_toy_data(seed=1)))
           .build())
    result = EarlyStoppingTrainer(cfg, net, _toy_data()).fit()
    assert result.termination_reason == TerminationReason.ITERATION_TERMINATION


def test_early_stopping_local_file_saver(tmp_path):
    net = _toy_net()
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
           .score_calculator(DataSetLossCalculator(_toy_data(seed=1)))
           .model_saver(LocalFileModelSaver(tmp_path))
           .build())
    result = EarlyStoppingTrainer(cfg, net, _toy_data()).fit()
    best = result.get_best_model()
    assert best is not None
    x = np.random.default_rng(4).normal(size=(4, 4))
    assert np.asarray(best.output(x)).shape == (4, 2)


def test_early_stopping_requires_termination_condition():
    net = _toy_net()
    cfg = (EarlyStoppingConfiguration.builder()
           .score_calculator(DataSetLossCalculator(_toy_data(seed=1)))
           .build())
    with pytest.raises(ValueError, match="termination"):
        EarlyStoppingTrainer(cfg, net, _toy_data()).fit()


# ----------------------------------------------------------------- solvers

@pytest.mark.parametrize("algo", [OptimizationAlgorithm.LINE_GRADIENT_DESCENT,
                                  OptimizationAlgorithm.CONJUGATE_GRADIENT,
                                  OptimizationAlgorithm.LBFGS])
def test_flat_solvers_reduce_loss(algo):
    net = _toy_net(algo=algo)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(32, 4))
    y = np.eye(2)[(x.sum(1) > 0).astype(int)]
    s0 = net.score(x, y)
    for _ in range(5):
        net.fit_batch(DataSet(x, y))
    assert net.score_value < s0
    assert np.isfinite(net.score_value)
    # the solver instance (and its compiled fns) must be reused across batches
    assert net._flat_solver is not None
    assert len(net._flat_solver._fns_cache) == 1


def test_flat_solver_computation_graph():
    from deeplearning4j_tpu import ComputationGraph
    conf = (NeuralNetConfiguration.builder().seed(9)
            .optimization_algo(OptimizationAlgorithm.LBFGS)
            .updater(Sgd(0.1))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    g = ComputationGraph(conf).init()
    rng = np.random.default_rng(10)
    x = rng.normal(size=(32, 4))
    y = np.eye(2)[(x.sum(1) > 0).astype(int)]
    s0 = g.score(DataSet(x, y))
    for _ in range(5):
        g.fit_batch(DataSet(x, y))
    assert g.score_value < s0


def test_flat_solver_updates_batchnorm_stats():
    from deeplearning4j_tpu import BatchNormalization
    conf = (NeuralNetConfiguration.builder().seed(11)
            .optimization_algo(OptimizationAlgorithm.LBFGS)
            .updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="identity"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(12)
    x = rng.normal(size=(32, 4)) * 3 + 1  # non-unit stats
    y = np.eye(2)[(x.sum(1) > 0).astype(int)]
    import jax
    before = jax.tree_util.tree_map(np.asarray, net.states)
    for _ in range(3):
        net.fit_batch(DataSet(x, y))
    after = net.states
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)))
    assert changed, "BatchNorm running stats must update under flat solvers"


def test_flat_solver_optimizes_current_batch_not_first():
    """Regression: the compiled solver fns must bind the CURRENT minibatch —
    a shape-keyed cache that captured the first batch silently optimized that
    batch forever (ADVICE.md round 1, high)."""
    net = _toy_net(algo=OptimizationAlgorithm.LBFGS)
    rng = np.random.default_rng(21)
    x1 = rng.normal(size=(32, 4))
    y1 = np.eye(2)[(x1.sum(1) > 0).astype(int)]
    x2 = rng.normal(size=(32, 4))
    y2 = np.eye(2)[(x2.sum(1) < 0).astype(int)]  # opposite labelling
    net.fit_batch(DataSet(x1, y1))               # fills the shape-keyed cache
    s2_before = net.score(x2, y2)
    for _ in range(10):
        net.fit_batch(DataSet(x2, y2))
    assert net.score(x2, y2) < s2_before, \
        "second batch's loss must go down when fitting the second batch"
    assert len(net._flat_solver._fns_cache) == 1  # same shapes -> one executable


def test_early_stopping_graph_trainer_in_memory_saver():
    """Regression: ComputationGraph.clone() must exist so the default
    InMemoryModelSaver can snapshot the best graph (ADVICE.md round 1, medium)."""
    from deeplearning4j_tpu import ComputationGraph
    from deeplearning4j_tpu.earlystopping import EarlyStoppingGraphTrainer
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Sgd(0.2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    g = ComputationGraph(conf).init()
    cfg = (EarlyStoppingConfiguration.builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .score_calculator(DataSetLossCalculator(_toy_data(seed=2)))
           .model_saver(InMemoryModelSaver())
           .build())
    result = EarlyStoppingGraphTrainer(cfg, g, _toy_data(seed=2)).fit()
    best = result.best_model
    assert best is not None and best is not g
    x = np.asarray(_toy_data(seed=2).next().features)
    np.testing.assert_allclose(np.asarray(best.output(x)[0]),
                               np.asarray(g.output(x)[0]), atol=1e-6)


def test_max_time_termination_fires_on_manual_clock():
    """GL001 regression: MaxTimeIterationTerminationCondition reads the
    injected util.time_source clock, so the wall budget expires under a
    ManualClock with zero real sleeps."""
    from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                     TimeSourceProvider)
    clock = ManualClock()
    TimeSourceProvider.set_instance(clock)
    try:
        cond = MaxTimeIterationTerminationCondition(max_time_seconds=30.0)
        cond.initialize()
        assert cond.terminate(score=1.0) is False
        clock.advance(29.0)
        assert cond.terminate(score=1.0) is False
        clock.advance(1.5)                       # 30.5s elapsed > 30s budget
        assert cond.terminate(score=1.0) is True
    finally:
        TimeSourceProvider.reset()
