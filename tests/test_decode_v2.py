"""Decode v2 tests: sampled decoding, paged KV, speculative verify.

- sampling: the traced top-k/top-p filter (keep_mask) against hand-built
  cases and against its numpy mirror (filter_probs_np), seeded streams
  reproducible / seed-sensitive, greedy short-circuit, and compile-flat
  executable counts while every sampling parameter swings per request
  (the GL016 invariant, asserted on XLA cache sizes).
- paged KV: BlockPool unit behavior (all-or-nothing alloc, double-free,
  defrag, high-water), flash_decode_paged == flash_decode on the gathered
  layout, and paged greedy/sampled decode == slab decode token-for-token
  for both model families.
- speculative: greedy parity with target-only decoding (attention and
  recurrent drafts), stop-id parity, seeded sampled determinism, verify
  probs == sequential step probs, recurrent targets rejected.
- scheduler: 2x-oversubscribed admission with forced preemption stays
  token-stream-invisible, pool accounting drains to zero, and the
  ManualClock fairness regression — deadline-expired and preempted slots
  retire through the SAME path, so the active_slots gauge and the block
  pool never leak (ISSUE 18 satellite).
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.decode import (BlockPool, DecodeEngine,
                                       DecodeScheduler, DecodeUnsupported,
                                       PoolExhausted, SamplerConfig,
                                       SpeculativeEngine, blocks_for)
from deeplearning4j_tpu.decode.sampling import (batch_operands,
                                                filter_probs_np, keep_mask)
from deeplearning4j_tpu.kernels import flash_decode, flash_decode_paged
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)
from deeplearning4j_tpu.zoo.models import char_rnn_lstm, transformer_lm

V = 24


def _tlm(seed=1, layers=1):
    net = transformer_lm(vocab_size=V, d_model=32, n_layers=layers,
                         n_heads=2, seed=seed)
    return net.init()


def _rnn(seed=2, layers=1):
    net = char_rnn_lstm(vocab_size=V, hidden=16, layers=layers, seed=seed)
    return net.init()


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


# ---------------------------------------------------------------- sampling

def test_sampler_config_validation_and_parsing():
    with pytest.raises(ValueError):
        SamplerConfig(temperature=float("nan"))
    with pytest.raises(ValueError):
        SamplerConfig(top_k=-1)
    with pytest.raises(ValueError):
        SamplerConfig(top_p=-0.1)
    assert SamplerConfig().is_greedy
    assert not SamplerConfig(temperature=0.7).is_greedy
    assert SamplerConfig.from_request({"prompt": [1]}) is None
    cfg = SamplerConfig.from_request({"temperature": 0.8, "seed": 9})
    assert cfg.temperature == 0.8 and cfg.seed == 9 and cfg.top_k == 0
    assert cfg.to_dict()["top_p"] == 1.0


def test_keep_mask_matches_numpy_mirror():
    """The traced filter and filter_probs_np keep the SAME support on
    random distributions across the parameter grid — the speculative
    engine's host-side accept math relies on this parity."""
    rng = np.random.default_rng(0)
    probs = rng.dirichlet(np.ones(V), size=6).astype(np.float32)
    for tk, tp in [(0, 1.0), (3, 1.0), (0, 0.5), (5, 0.7), (1, 0.0),
                   (V, 1.0), (0, 0.0)]:
        mask = np.asarray(keep_mask(
            jnp.asarray(probs),
            jnp.full((6,), tk, np.int32),
            jnp.full((6,), tp, np.float32)))
        for b in range(6):
            cfg = SamplerConfig(temperature=1.0, top_k=tk, top_p=tp)
            support = filter_probs_np(probs[b], cfg) > 0
            assert (mask[b] == support).all(), (tk, tp, b)


def test_keep_mask_edges():
    probs = jnp.asarray([[0.5, 0.3, 0.1, 0.06, 0.04]], jnp.float32)

    def km(tk, tp):
        return np.asarray(keep_mask(probs,
                                    jnp.asarray([tk], jnp.int32),
                                    jnp.asarray([tp], jnp.float32)))[0]

    # top_k keeps exactly the k largest; 0 and >=V disable
    assert km(2, 1.0).tolist() == [True, True, False, False, False]
    assert km(0, 1.0).all() and km(5, 1.0).all()
    # top_p=0 still keeps the top-1 token (never an empty support)
    assert km(0, 0.0).tolist() == [True, False, False, False, False]
    # exclusive-cumsum nucleus: p=0.8 keeps {0.5, 0.3} (excl cumsum 0,
    # 0.5) and also 0.1 (excl cumsum 0.8 is NOT < 0.8 -> excluded)
    assert km(0, 0.8).tolist() == [True, True, False, False, False]
    # filters compose: top_k=1 wins over a loose top_p
    assert km(1, 0.99).tolist() == [True, False, False, False, False]


def test_seeded_generate_reproducible_and_seed_sensitive():
    net = _tlm(seed=4)
    s42 = SamplerConfig(temperature=0.9, top_k=8, top_p=0.95, seed=42)
    a = net.generate([3, 1, 4], 12, sampler=s42)
    b = net.generate([3, 1, 4], 12,
                     sampler=SamplerConfig(temperature=0.9, top_k=8,
                                           top_p=0.95, seed=42))
    c = net.generate([3, 1, 4], 12,
                     sampler=SamplerConfig(temperature=0.9, top_k=8,
                                           top_p=0.95, seed=43))
    assert a == b
    assert a != c
    # temperature 0 short-circuits to greedy regardless of other params
    g = net.generate([3, 1, 4], 12,
                     sampler=SamplerConfig(temperature=0.0, seed=42))
    assert g == net.generate([3, 1, 4], 12)


def test_sampling_params_swing_compile_flat():
    """ISSUE acceptance: swinging temperature/top_k/top_p/seed across
    requests leaves every decode executable's XLA cache at exactly 1 —
    sampling params are operands, never keys (GL016)."""
    net = _tlm(seed=5)
    eng = DecodeEngine(net, slots=2, max_len=48)
    rng = np.random.default_rng(0)
    outs = set()
    for i in range(6):
        cfg = SamplerConfig(temperature=0.3 + 0.2 * i,
                            top_k=int(rng.integers(0, V)),
                            top_p=float(rng.uniform(0.5, 1.0)),
                            seed=i)
        outs.add(tuple(eng.generate([2, 7, 1], 6, sampler=cfg)))
    eng.generate([2, 7, 1], 6)                      # greedy co-resident
    counts = eng.executable_counts()
    assert all(v == 1 for v in counts.values()), counts
    assert len(outs) > 1      # the params actually changed the streams


# ---------------------------------------------------------------- paged KV

def test_block_pool_unit():
    pool = BlockPool(8, 16)                 # block 0 is scratch
    assert pool.capacity_blocks == 7 and pool.free_blocks == 7
    a = pool.alloc(3)
    assert len(a) == 3 and 0 not in a
    assert pool.used_blocks == 3
    with pytest.raises(PoolExhausted):
        pool.alloc(5)                       # all-or-nothing: 4 free
    assert pool.used_blocks == 3            # failed alloc took nothing
    b = pool.alloc(4)
    assert pool.free_blocks == 0 and pool.high_water == 7
    assert 0.99 < pool.utilization() <= 1.0
    pool.free(a)
    assert pool.free_blocks == 3
    with pytest.raises(ValueError):
        pool.free(a)                        # double free
    with pytest.raises(ValueError):
        pool.free([0])                      # scratch is not freeable
    pool.free(b)
    pool.defrag()
    assert pool.free_blocks == 7 and pool.used_blocks == 0
    assert pool.high_water == 7             # high-water survives drain
    assert blocks_for(1, 16) == 1 and blocks_for(16, 16) == 1
    assert blocks_for(17, 16) == 2 and blocks_for(0, 16) == 0


def test_flash_decode_paged_matches_slab():
    """Gather+flash on the paged pool == flash_decode on the equivalent
    slab, under jit, for ragged per-slot lengths."""
    S, H, D, bs, nb = 3, 2, 8, 4, 4         # capacity 16 tokens per slot
    rng = np.random.default_rng(1)
    cap = bs * nb
    k_slab = rng.standard_normal((S, cap, H, D)).astype(np.float32)
    v_slab = rng.standard_normal((S, cap, H, D)).astype(np.float32)
    q = rng.standard_normal((S, 1, H, D)).astype(np.float32)
    lengths = np.asarray([5, 16, 1], np.int32)
    # scatter the slabs into a pool via a known table (block 0 = scratch)
    pool_k = np.zeros((1 + S * nb, bs, H, D), np.float32)
    pool_v = np.zeros_like(pool_k)
    table = np.zeros((S, nb), np.int32)
    for s in range(S):
        for j in range(nb):
            blk = 1 + s * nb + j
            table[s, j] = blk
            pool_k[blk] = k_slab[s, j * bs:(j + 1) * bs]
            pool_v[blk] = v_slab[s, j * bs:(j + 1) * bs]
    ref = np.asarray(flash_decode(jnp.asarray(q), jnp.asarray(k_slab),
                                  jnp.asarray(v_slab),
                                  jnp.asarray(lengths), use_pallas=False))
    got = np.asarray(jax.jit(
        lambda *a: flash_decode_paged(*a, use_pallas=False))(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(lengths)))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("make,label", [(_tlm, "attention"),
                                        (_rnn, "recurrent")])
def test_paged_engine_matches_slab_both_families(make, label):
    net = make(seed=6)
    prompt = [3, 1, 4, 1, 5]
    slab = DecodeEngine(net, slots=2, max_len=48)
    paged = DecodeEngine(net, slots=2, max_len=48, paged=True, block_size=8)
    assert paged.generate(prompt, 10) == slab.generate(prompt, 10), label
    cfg = SamplerConfig(temperature=0.8, top_k=6, seed=7)
    assert paged.generate(prompt, 10, sampler=cfg) == \
        slab.generate(prompt, 10, sampler=cfg), label
    counts = paged.executable_counts()
    assert all(n == 1 for n in counts.values()), counts


# ------------------------------------------------------------- speculative

def test_verify_probs_match_sequential_steps():
    """One batched verify pass returns the same next-token distributions
    the step executable would produce one token at a time."""
    net = _tlm(seed=7, layers=2)
    prompt = [2, 9, 4]
    window = [7, 3, 8, 1]
    eng = DecodeEngine(net, slots=1, max_len=32)
    cache = eng.init_cache()
    cache, _, _ = eng.prefill(cache, 0, prompt)
    # vprobs[i] is the distribution AFTER consuming window[i], so the
    # sequential oracle steps each window token in turn
    seq_rows = []
    ids = np.zeros((1,), np.int32)
    for t in window:
        ids[0] = t
        cache, _, pp = eng.step(cache, ids)
        seq_rows.append(np.asarray(pp[0]))
    cache2 = eng.init_cache()
    cache2, _, _ = eng.prefill(cache2, 0, prompt)
    cache2, vprobs = eng.verify(cache2, 0, window, len(prompt))
    vprobs = np.asarray(vprobs)
    assert vprobs.shape == (len(window), V)
    for i in range(len(window)):
        np.testing.assert_allclose(vprobs[i], seq_rows[i], atol=2e-4)


@pytest.mark.parametrize("mkdraft,label", [(_rnn, "recurrent-draft"),
                                           (lambda **kw: _tlm(**kw),
                                            "attention-draft")])
def test_speculative_greedy_parity(mkdraft, label):
    """ISSUE acceptance: greedy speculative == target-only greedy,
    token-for-token, even with an UNRELATED draft (acceptance ~0 — the
    correction path carries every token)."""
    target = _tlm(seed=8, layers=2)
    draft = mkdraft(seed=15)
    ref = target.generate([5, 2, 6], 14)
    spec = SpeculativeEngine(draft, target, k=3, max_len=64)
    assert spec.generate([5, 2, 6], 14) == ref, label
    # the prefill emits the first token outside the round loop
    assert spec.rounds > 0 and spec.emitted >= 13
    counts = spec.executable_counts()
    assert all(n == 1 for n in counts.values()), counts


def test_speculative_stop_id_and_sampled_determinism():
    target = _tlm(seed=8, layers=2)
    draft = _tlm(seed=16)
    full = target.generate([4, 4, 1], 10)
    stop = full[2]
    spec = SpeculativeEngine(draft, target, k=3, max_len=64)
    assert spec.generate([4, 4, 1], 10, stop_id=stop) == \
        target.generate([4, 4, 1], 10, stop_id=stop)
    # sampled mode: per-seed deterministic (same distribution as target-
    # only sampling, but a different draw — greedy is the parity mode)
    cfg = SamplerConfig(temperature=0.9, top_p=0.9, seed=5)
    s1 = spec.generate([4, 4, 1], 10, sampler=cfg)
    s2 = spec.generate([4, 4, 1], 10, sampler=cfg)
    assert s1 == s2


def test_speculative_guards():
    with pytest.raises(DecodeUnsupported):
        SpeculativeEngine(_tlm(seed=1), _rnn(seed=2))   # recurrent target
    net = _tlm(seed=1)
    with pytest.raises(ValueError):
        SpeculativeEngine(net, net)                     # self-draft
    eng = DecodeEngine(_rnn(seed=3), slots=1, max_len=16)
    with pytest.raises(DecodeUnsupported):
        eng.verify(eng.init_cache(), 0, [1, 2], 0)      # recurrent verify


# ---------------------------------------------------------------- scheduler

def _scheduler(net, version="v1", slots=3, max_len=64, **kw):
    registry = ModelRegistry()
    registry.register(version, net)
    registry.deploy(version)
    mreg = MetricsRegistry()
    sched = DecodeScheduler(registry, mreg, slots=slots, max_len=max_len,
                            **kw)
    return sched, registry, mreg


def test_oversubscribed_scheduler_parity_with_forced_preemption():
    """2x-oversubscribed paged admission with budgets long enough to
    force preemptions: every stream equals its slab run (preempt/requeue
    is token-stream-invisible, greedy AND seeded-sampled), the preempt
    counter moved, and the pool drains to zero."""
    net = _tlm(seed=9, layers=2)
    prompts = [[3, 1, 4, 1, 5], [9, 2], [6, 6, 7, 2, 1, 8]]
    # ~45-token contexts x 3 = ~18 blocks of 8 wanted, 9 allocatable:
    # concurrent growth MUST steal from the youngest
    budgets = [40, 40, 40]
    cfgs = [None, SamplerConfig(temperature=0.8, seed=11), None]
    slab, _, _ = _scheduler(net, slots=3, max_len=64)
    slab.start()
    try:
        want = [slab.generate(p, max_new_tokens=n, sampler=c)["tokens"]
                for p, n, c in zip(prompts, budgets, cfgs)]
    finally:
        slab.stop()
    # 9 allocatable blocks of 8 over 3 slots of capacity 64: each slot
    # wants up to 8 blocks, so concurrent growth must steal
    sched, _, mreg = _scheduler(net, slots=3, max_len=64, paged=True,
                                block_size=8, pool_blocks=10)
    sched.start()
    try:
        futs = [sched.submit(p, max_new_tokens=n, sampler=c)
                for p, n, c in zip(prompts, budgets, cfgs)]
        got = [f.result(timeout=300)["tokens"] for f in futs]
        assert got == want
        assert mreg.get("decode_preempted_total").get() >= 1
        snap = sched.snapshot()
        assert snap["paged"]["used_blocks"] == 0
        assert snap["active_slots"] == 0
    finally:
        sched.stop()


def test_fairness_deadline_and_preempt_share_retire_path(manual_clock):
    """ISSUE satellite: a preempted-then-requeued request whose deadline
    expires retires through the SAME path as a mid-generation deadline —
    partial tokens returned with finish_reason='deadline' (not a 504) —
    and neither preempt nor expiry leaks slots, blocks, or the
    active_slots gauge. Driven synchronously (no loop thread) under
    ManualClock for a deterministic preempt->requeue->expire sequence."""
    net = _tlm(seed=10)
    sched, _, mreg = _scheduler(net, slots=2, max_len=32, paged=True,
                                block_size=8, pool_blocks=5)
    # 4 allocatable blocks; two slots of up to 4 blocks each
    f1 = sched.submit([1, 2, 3], max_new_tokens=20)
    f2 = sched.submit([4, 5, 6], max_new_tokens=20, timeout_ms=5000.0)
    sched._admit()
    assert sched.active_count() == 2
    preempted_at = None
    for _ in range(40):
        sched._step_wave()
        sched._admit()
        if mreg.get("decode_preempted_total").get() >= 1 \
                and preempted_at is None:
            preempted_at = True
            # r2 (youngest) lost its slot mid-flight with partial tokens
            # and is re-queued; active gauge reflects the release
            assert sched.active_count() == 1
            assert mreg.get("decode_active_slots").get() == 1
            # its deadline now expires while it waits in the queue
            manual_clock.advance(6.0)
        if f1.done() and f2.done():
            break
    assert preempted_at, "pool never forced a preemption"
    r1 = f1.result(timeout=0)
    r2 = f2.result(timeout=0)
    assert r1["finish_reason"] == "length" and len(r1["tokens"]) == 20
    # partial result, SAME retire path as a mid-generation deadline
    assert r2["finish_reason"] == "deadline"
    assert 0 < len(r2["tokens"]) < 20
    assert sched.active_count() == 0
    assert mreg.get("decode_active_slots").get() == 0
    snap = sched.snapshot()
    assert snap["paged"]["used_blocks"] == 0
    assert set(sched._free) == {0, 1}       # both slot ids back


def test_mid_generation_deadline_returns_partial(manual_clock):
    """The budget-spent path (no preemption involved): tokens stop at the
    deadline, partial result, slot released — the baseline the fairness
    test compares against."""
    net = _tlm(seed=10)
    sched, _, mreg = _scheduler(net, slots=1, max_len=32)
    f = sched.submit([1, 2, 3], max_new_tokens=20, timeout_ms=2000.0)
    sched._admit()
    sched._step_wave()
    manual_clock.advance(3.0)
    sched._step_wave()
    r = f.result(timeout=0)
    assert r["finish_reason"] == "deadline"
    assert 0 < len(r["tokens"]) < 20
    assert sched.active_count() == 0
    assert mreg.get("decode_active_slots").get() == 0


# --------------------------------------------------------------- smoke tool

def test_smoke_decode_v2_tool():
    """End-to-end Decode v2 smoke (seeded sampling across hot-swap,
    2x-oversubscribed admission with zero 5xx, speculative greedy
    parity) — fast variant of tools/smoke_decode_v2.py, mirroring the
    smoke_decode wiring."""
    import tools.smoke_decode_v2 as smoke
    out = smoke.run(n_requests=6)
    assert out["sampling"]["steady_state_compiles"] == 0
    assert out["sampling"]["hot_swap_stable"]
    assert out["paged"]["errors_5xx"] == 0 and out["paged"]["parity_ok"]
    assert out["paged"]["pool_drained"]
    assert out["speculative"]["greedy_parity"]
    assert out["speculative"]["acceptance_rate"] > 0
    assert out["donation_warnings"] == 0
