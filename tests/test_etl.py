"""ETL subsystem tests: schema/transform serialization, streaming
normalizers, the parallel pipeline executor (ordering, backpressure, error
propagation, telemetry), sharded device prefetch, and the end-to-end
CSV -> TransformProcess -> DataNormalizer -> ParallelPipelineExecutor ->
DevicePrefetcher -> network.fit acceptance path.

Mirrors the coverage the reference stack gets from the external DataVec
library's transform tests (org.datavec.api.transform.*) plus nd4j's
NormalizerStandardize/MinMaxScaler tests — here with the TPU-specific
additions: vectorized batch execution, mesh-sharded placement, and the
consumer wait-time histogram (deterministic via util.time_source
.ManualClock).
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator.base import ListDataSetIterator
from deeplearning4j_tpu.datasets.records.reader import (CollectionRecordReader,
                                                        RecordReader)
from deeplearning4j_tpu.etl import (ColumnType, DataNormalizer,
                                    DevicePrefetcher, NormalizerMinMaxScaler,
                                    NormalizerStandardize,
                                    ParallelPipelineExecutor, Schema,
                                    TransformProcess)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


def _demo_schema():
    return (Schema.builder().add_numeric("a", "b")
            .add_categorical("color", ["red", "green", "blue"])
            .add_integer("label").build())


# ------------------------------------------------------------------- schema

def test_schema_builder_and_json_round_trip():
    s = _demo_schema()
    assert s.names() == ["a", "b", "color", "label"]
    assert s.column("color").kind == ColumnType.CATEGORICAL
    assert s.column("color").categories == ["red", "green", "blue"]
    assert s.index_of("label") == 3
    s2 = Schema.from_json(s.to_json())
    assert s2 == s

    with pytest.raises(ValueError):
        Schema.builder().add_numeric("x", "x").build()   # duplicate names


def test_schema_batch_round_trip():
    s = _demo_schema()
    recs = [[1.0, 2.0, "red", 0], [3.0, 4.0, "blue", 2]]
    batch = s.to_batch(recs)
    assert batch["a"].dtype == np.float64
    assert batch["label"].dtype == np.int64
    assert list(batch["color"]) == ["red", "blue"]
    assert s.to_records(batch) == recs


# ---------------------------------------------------------------- transform

def test_transform_ops_chain():
    tp = (TransformProcess.builder(_demo_schema())
          .categorical_to_one_hot("color")
          .derived_column("ab", "mul", ["a", "b"])
          .min_max_normalize("a", 0.0, 10.0)
          .rename_column("b", "bee")
          .remove_columns("label")
          .build())
    assert tp.final_schema().names() == [
        "a", "bee", "color[red]", "color[green]", "color[blue]", "ab"]
    out = tp.execute([[5.0, 3.0, "green", 1]])
    np.testing.assert_allclose(out[0], [0.5, 3.0, 0.0, 1.0, 0.0, 15.0])


def test_transform_filter_and_categorical_to_integer():
    tp = (TransformProcess.builder(_demo_schema())
          .filter_rows("a", "lt", 0.0)          # REMOVE rows where a < 0
          .categorical_to_integer("color")
          .standardize("b", mean=2.0, std=2.0)
          .build())
    out = tp.execute([[1.0, 4.0, "blue", 0],
                      [-1.0, 0.0, "red", 1],    # filtered out
                      [2.0, 0.0, "red", 2]])
    assert len(out) == 2
    np.testing.assert_allclose(out[0], [1.0, 1.0, 2, 0])
    np.testing.assert_allclose(out[1], [2.0, -1.0, 0, 2])
    assert tp.final_schema().column("color").kind == ColumnType.INTEGER


def test_transform_json_round_trip_and_equality():
    tp = (TransformProcess.builder(_demo_schema())
          .categorical_to_one_hot("color")
          .filter_rows("a", "ge", 100.0)
          .derived_column("lg", "log", ["b"])
          .standardize("a", 1.0, 2.0)
          .sequence_window(4, 2)
          .build())
    tp2 = TransformProcess.from_json(tp.to_json())
    assert tp2 == tp
    assert tp2.final_schema() == tp.final_schema()
    recs = [[float(i), float(i + 1), "red", 0] for i in range(8)]
    b1 = tp.execute_batch(tp.initial_schema.to_batch(recs))
    b2 = tp2.execute_batch(tp2.initial_schema.to_batch(recs))
    for k in b1:
        np.testing.assert_allclose(b1[k].astype(float),
                                   b2[k].astype(float))


def test_transform_validates_eagerly():
    with pytest.raises(KeyError):
        TransformProcess.builder(_demo_schema()) \
            .standardize("missing", 0, 1).build()
    with pytest.raises(ValueError):
        # sequence_window over a still-categorical column
        TransformProcess.builder(_demo_schema()).sequence_window(2).build()


def test_sequence_window_assembles_time_major():
    schema = Schema.builder().add_numeric("x", "y").build()
    tp = (TransformProcess.builder(schema)
          .sequence_window(3, 1).build())
    reader = CollectionRecordReader(
        [[float(i), float(10 * i)] for i in range(6)])
    ex = ParallelPipelineExecutor(reader, tp, batch_size=6, workers=1,
                                  registry=MetricsRegistry())
    ds = ex.next()
    assert ds.features.shape == (4, 3, 2)     # [windows, time, features]
    np.testing.assert_allclose(ds.features[1, :, 0], [1, 2, 3])
    np.testing.assert_allclose(ds.features[1, :, 1], [10, 20, 30])
    ex.close()


# --------------------------------------------------------------- normalizer

def test_standardize_streaming_matches_whole_data():
    rng = np.random.default_rng(0)
    data = rng.normal(3.0, 2.0, size=(257, 5)).astype(np.float32)
    it = ListDataSetIterator(DataSet(data, data).batch_by(16))  # ragged tail
    nz = NormalizerStandardize().fit(it)
    np.testing.assert_allclose(nz.mean, data.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(nz.std, data.std(axis=0, ddof=1), rtol=1e-4)
    out = nz.transform(DataSet(data, data))
    assert abs(float(out.features.mean())) < 1e-5
    back = nz.revert(out)
    np.testing.assert_allclose(back.features, data, atol=1e-4)
    # labels untouched unless fit_labels
    np.testing.assert_allclose(out.labels, data)


def test_min_max_scaler_and_fit_labels():
    x = np.array([[0.0, 10.0], [5.0, 20.0], [10.0, 40.0]], np.float32)
    y = np.array([[1.0], [2.0], [3.0]], np.float32)
    nz = NormalizerMinMaxScaler(fit_labels=True).fit(DataSet(x, y))
    out = nz.transform(DataSet(x, y))
    np.testing.assert_allclose(out.features,
                               [[0, 0], [0.5, 1 / 3], [1, 1]], atol=1e-6)
    np.testing.assert_allclose(out.labels, [[0], [0.5], [1]], atol=1e-6)
    np.testing.assert_allclose(nz.revert_labels(out.labels), y, atol=1e-6)
    rt = DataNormalizer.from_json(nz.to_json())
    np.testing.assert_allclose(rt.transform(DataSet(x, y)).features,
                               out.features, atol=1e-6)


def test_normalizer_rides_in_model_zip(tmp_path):
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, Sgd)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    data = np.random.default_rng(1).normal(5, 3, (32, 3)).astype(np.float32)
    nz = NormalizerStandardize().fit(DataSet(data, data))
    p = str(tmp_path / "model.zip")
    ModelSerializer.write_model(net, p, normalizer=nz)
    rt = ModelSerializer.restore_normalizer(p)
    assert isinstance(rt, NormalizerStandardize)
    np.testing.assert_allclose(rt.mean, nz.mean, rtol=1e-6)
    # a zip without one: None
    p2 = str(tmp_path / "bare.zip")
    ModelSerializer.write_model(net, p2)
    assert ModelSerializer.restore_normalizer(p2) is None
    # add_normalizer retrofits an existing zip
    ModelSerializer.add_normalizer(p2, nz)
    assert ModelSerializer.restore_normalizer(p2) is not None
    assert ModelSerializer.read_format(p2)["model_class"] \
        == "MultiLayerNetwork"


# ----------------------------------------------------------------- pipeline

def _simple_records(n, width=3):
    return [[float(i)] * width for i in range(n)]


def test_pipeline_ordered_matches_sequential():
    recs = _simple_records(40)
    ex = ParallelPipelineExecutor(CollectionRecordReader(recs),
                                  batch_size=8, workers=4, ordered=True,
                                  registry=MetricsRegistry())
    batches = list(ex)
    assert len(batches) == 5
    flat = np.concatenate([b.features for b in batches])
    np.testing.assert_allclose(flat, np.asarray(recs, np.float32))
    # reset replays identically
    ex.reset()
    flat2 = np.concatenate([b.features for b in ex])
    np.testing.assert_allclose(flat2, flat)
    ex.close()


def test_pipeline_unordered_vs_ordered_delivery():
    """Chunk 0's worker blocks until chunk 1 has been PROCESSED: unordered
    delivery hands the consumer chunk 1 first, ordered delivery still waits
    for chunk 0."""
    def make(ordered):
        gate = threading.Event()

        def assemble(records):
            tag = records[0][0]
            if tag == 0.0:
                assert gate.wait(20), "chunk 1 never processed"
            else:
                gate.set()
            arr = np.full((len(records), 2), tag, np.float32)
            return DataSet(arr, arr)
        reader = CollectionRecordReader([[0.0], [0.0], [1.0], [1.0]])
        return ParallelPipelineExecutor(reader, batch_size=2, workers=2,
                                        ordered=ordered, assemble=assemble,
                                        registry=MetricsRegistry())

    ex = make(ordered=False)
    first = ex.next().features[0, 0]
    assert first == 1.0                       # fast chunk overtakes
    assert ex.next().features[0, 0] == 0.0
    ex.close()

    ex = make(ordered=True)
    assert ex.next().features[0, 0] == 0.0    # source order preserved
    assert ex.next().features[0, 0] == 1.0
    ex.close()


def test_pipeline_filtered_out_chunk_is_skipped():
    schema = Schema.builder().add_numeric("x").build()
    tp = (TransformProcess.builder(schema)
          .filter_rows("x", "lt", 2.0).build())     # removes records 0, 1
    ex = ParallelPipelineExecutor(CollectionRecordReader(_simple_records(6, 1)),
                                  tp, batch_size=2, workers=2,
                                  registry=MetricsRegistry())
    batches = list(ex)
    flat = sorted(float(v) for b in batches for v in b.features.ravel())
    assert flat == [2.0, 3.0, 4.0, 5.0]       # chunk 0 fully filtered away
    ex.close()


class _BoomReader(RecordReader):
    """Fails at record `boom` on the first pass only."""

    def __init__(self, n, boom, exc=None):
        self.n, self.boom = n, boom
        self.exc = exc or RuntimeError("reader exploded")
        self._i = 0
        self._armed = True

    def has_next(self):
        return self._i < self.n

    def next_record(self):
        if self._armed and self._i == self.boom:
            raise self.exc
        self._i += 1
        return [float(self._i)]

    def reset(self):
        self._i = 0
        self._armed = False


def test_pipeline_reader_error_reaches_consumer_exactly_once():
    ex = ParallelPipelineExecutor(_BoomReader(20, boom=10), batch_size=2,
                                  workers=2, registry=MetricsRegistry())
    with pytest.raises(RuntimeError, match="reader exploded"):
        list(ex)
    assert not ex.has_next()                  # no double raise
    ex.close()                                # no double raise here either


def test_pipeline_runtimeerror_from_reader_is_not_swallowed():
    """RuntimeError is also what a closed MagicQueue raises internally; a
    reader's own RuntimeError must still reach the consumer."""
    ex = ParallelPipelineExecutor(
        _BoomReader(20, boom=4, exc=RuntimeError("custom runtime issue")),
        batch_size=2, workers=1, registry=MetricsRegistry())
    with pytest.raises(RuntimeError, match="custom runtime issue"):
        list(ex)
    ex.close()


def test_pipeline_worker_error_surfaces_on_close_when_consumer_stopped():
    """A transform failure after the consumer stops pulling must not be
    swallowed: close() re-raises it (exactly once)."""
    def assemble(records):
        if records[0][0] >= 4.0:
            raise ValueError("transform exploded")
        arr = np.asarray(records, np.float32)
        return DataSet(arr, arr)

    ex = ParallelPipelineExecutor(CollectionRecordReader(_simple_records(8, 1)),
                                  batch_size=2, workers=1, assemble=assemble,
                                  ordered=True, registry=MetricsRegistry())
    assert ex.next().num_examples() == 2      # consume one batch, then stop
    deadline = time.monotonic() + 20
    while not ex._out.has_error() and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ValueError, match="transform exploded"):
        ex.close()
    ex.close()                                # second close: clean no-op


def test_pipeline_close_mid_stream_then_reset():
    """Deterministic close(): stops with batches still queued, joins all
    threads; reset() afterwards restarts a full clean pass."""
    ex = ParallelPipelineExecutor(CollectionRecordReader(_simple_records(64)),
                                  batch_size=4, workers=3, queue_capacity=2,
                                  registry=MetricsRegistry())
    assert ex.next() is not None
    ex.close()
    assert all(not t.is_alive() for t in ex._threads)
    assert not ex.has_next()
    ex.reset()
    assert sum(1 for _ in ex) == 16
    ex.close()


def test_pipeline_inline_mode_and_telemetry_counters():
    reg = MetricsRegistry()
    ex = ParallelPipelineExecutor(CollectionRecordReader(_simple_records(20)),
                                  batch_size=5, workers=0, name="inline",
                                  registry=reg)
    assert sum(1 for _ in ex) == 4
    assert reg.counter("etl_batches_total").get(pipeline="inline") == 4
    assert reg.counter("etl_records_total").get(pipeline="inline") == 20
    assert reg.histogram("etl_consumer_wait_ms").count(pipeline="inline") > 0


class _SlowClockReader(RecordReader):
    """Reader whose per-record cost exists only on the ManualClock: each
    record advances the fake clock by `cost_s` — the deterministic stand-in
    for a slow decode/augment stage."""

    def __init__(self, n, clock, cost_s, width=3):
        self.n, self.clock, self.cost_s, self.width = n, clock, cost_s, width
        self._i = 0

    def has_next(self):
        return self._i < self.n

    def next_record(self):
        self.clock.advance(self.cost_s)
        self._i += 1
        return [float(self._i)] * self.width

    def reset(self):
        self._i = 0


def test_consumer_wait_histogram_shrinks_with_prefetch(manual_clock):
    """The acceptance metric for the whole subsystem: with the pipeline
    prefetching (workers > 0, buffered), the consumer's recorded wait is ~0;
    with everything inline (workers=0), the consumer waits for the full
    read cost of every batch. Deterministic via ManualClock — the only
    clock advances are the slow reader's."""
    n_batches, batch, cost_s = 4, 8, 0.005
    reg = MetricsRegistry()

    # ---- prefetch OFF: inline stages run inside next() -------------------
    ex = ParallelPipelineExecutor(
        _SlowClockReader(n_batches * batch, manual_clock, cost_s),
        batch_size=batch, workers=0, name="off", registry=reg)
    assert sum(1 for _ in ex) == n_batches
    off = reg.histogram("etl_consumer_wait_ms")
    off_sum = off.sum(pipeline="off")
    assert off_sum >= n_batches * batch * cost_s * 1000.0 * 0.99

    # ---- prefetch ON: buffer everything, then consume --------------------
    ex = ParallelPipelineExecutor(
        _SlowClockReader(n_batches * batch, manual_clock, cost_s),
        batch_size=batch, workers=2, queue_capacity=n_batches + 1,
        name="on", registry=reg)
    deadline = time.monotonic() + 20
    while ex._out.depth() < n_batches and time.monotonic() < deadline:
        time.sleep(0.01)                     # real time; fake clock frozen
    assert sum(1 for _ in ex) == n_batches
    on_sum = reg.histogram("etl_consumer_wait_ms").sum(pipeline="on")
    assert on_sum < off_sum * 0.01, \
        f"prefetch-on wait {on_sum}ms not << prefetch-off wait {off_sum}ms"
    ex.close()


# ----------------------------------------------------------- device prefetch

def test_device_prefetcher_batches_are_resident():
    import jax
    data = DataSet(np.ones((16, 4), np.float32), np.ones((16, 2), np.float32))
    pf = DevicePrefetcher(ListDataSetIterator(data.batch_by(4)), queue_size=2,
                          registry=MetricsRegistry())
    seen = list(pf)
    assert len(seen) == 4
    for ds in seen:
        assert isinstance(ds.features, jax.Array)
        assert ds.features.devices() == {jax.devices()[0]}
    pf.close()


def test_device_prefetcher_sharded_placement():
    """Acceptance: sharded prefetch places each batch shard on its mesh
    device — asserted via .devices() / committed placement."""
    import jax
    from deeplearning4j_tpu.parallel.sharding import (DATA_AXIS,
                                                      batch_sharding,
                                                      make_mesh)
    mesh = make_mesh()
    n_dev = mesh.shape[DATA_AXIS]
    assert n_dev == 8                       # conftest virtual mesh
    data = DataSet(np.random.default_rng(0).normal(size=(32, 4))
                   .astype(np.float32),
                   np.ones((32, 2), np.float32))
    pf = DevicePrefetcher(ListDataSetIterator(data.batch_by(16)),
                          queue_size=3, mesh=mesh,
                          registry=MetricsRegistry())
    for ds in pf:
        for arr in (ds.features, ds.labels):
            assert set(arr.devices()) == set(mesh.devices.ravel())
            assert arr.sharding == batch_sharding(mesh, arr.ndim)
            assert arr.committed
            # each device holds exactly its 1/n_dev slice of the batch
            for shard in arr.addressable_shards:
                assert shard.data.shape[0] == arr.shape[0] // n_dev
    pf.close()


def test_device_prefetcher_non_divisible_batch_falls_back_unsharded():
    from deeplearning4j_tpu.parallel.sharding import make_mesh
    data = DataSet(np.ones((10, 4), np.float32), np.ones((10, 2), np.float32))
    pf = DevicePrefetcher(ListDataSetIterator([data.slice(0, 10)]),
                          mesh=make_mesh(), registry=MetricsRegistry())
    ds = pf.next()
    assert len(ds.features.devices()) == 1   # unsharded put; trainer pads
    pf.close()


def test_device_prefetcher_error_on_close_exactly_once():
    class Boom(ListDataSetIterator):
        def next(self):
            if self._i == 1:
                raise RuntimeError("producer died")
            return super().next()

    data = DataSet(np.ones((12, 3), np.float32))
    pf = DevicePrefetcher(Boom(data.batch_by(4)), queue_size=4,
                          registry=MetricsRegistry())
    pf.next()                                # consumer pulls once, then stops
    deadline = time.monotonic() + 20
    while pf._error is None and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="producer died"):
        pf.close()
    pf.close()                               # second close: clean


def test_fit_prefetch_knob():
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, Adam)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    it = ListDataSetIterator(DataSet(x, y).batch_by(32))
    net.fit(it, epochs=10, prefetch=2)
    assert net.evaluate(it).accuracy() > 0.9


# ------------------------------------------------------------- end to end

def test_smoke_etl_tool():
    """CSV -> TransformProcess -> normalizer -> parallel pipeline -> device
    prefetch -> network.fit, with zero steady-state recompiles (fast
    variant of tools/smoke_etl.py, mirroring smoke_serving/smoke_telemetry
    wiring)."""
    import tools.smoke_etl as smoke
    out = smoke.run(n_rows=256, workers=2, epochs=6)
    assert out["accuracy"] > 0.9
    assert out["steady_state_recompiles"] == 0
    assert out["etl_batches_total"] > 0


def test_derived_column_binary_without_scalar_fails_at_build():
    """Regression: a binary derive fn with one column and no scalar must be
    rejected at build time, not explode in a worker thread at batch N."""
    schema = Schema.builder().add_numeric("x").build()
    with pytest.raises(ValueError, match="scalar"):
        TransformProcess.builder(schema) \
            .derived_column("x2", "mul", ["x"]).build()
    # unary fns and column+scalar forms stay valid
    TransformProcess.builder(schema).derived_column("lx", "log", ["x"]).build()
    TransformProcess.builder(schema) \
        .derived_column("x2", "mul", ["x"], scalar=2.0).build()


def test_pipeline_label_config_validated_at_build():
    """Regression: label routing without a TransformProcess used to be
    silently ignored (model trains on wrong data); one_hot_labels without a
    label column used to IndexError in a worker at batch time."""
    reader = CollectionRecordReader(_simple_records(4))
    with pytest.raises(ValueError, match="TransformProcess"):
        ParallelPipelineExecutor(reader, label_columns=["label"],
                                 registry=MetricsRegistry())
    with pytest.raises(ValueError, match="label_columns"):
        schema = Schema.builder().add_numeric("a", "b", "c").build()
        tp = TransformProcess.builder(schema).build()
        ParallelPipelineExecutor(reader, tp, one_hot_labels=3,
                                 registry=MetricsRegistry())
