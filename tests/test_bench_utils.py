"""Unit tests for bench.py's measurement self-defense (pure logic only —
no device): the interleaved min-difference timer must cancel a bimodal
per-call floor and survive relay outages via its resample self-check, and
the regression detector must compare against the best prior BENCH_r*.json
with the renamed-metric mapping applied."""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest


@pytest.fixture(scope="module")
def bench():
    root = os.path.join(os.path.dirname(__file__), os.pardir)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_diff_time_cancels_bimodal_floor(bench):
    """Per-call cost = signal*depth + floor, floor drawn from {60ms, 105ms}
    at random per CALL (a harsher model than the rig, whose phases persist
    across calls): min(t_2K) − min(t_K) over interleaved samples recovers
    the pure K-step signal once both groups sample the low mode."""
    rng = np.random.default_rng(0)
    sig = 0.020                       # 20 ms of true K-step signal

    def runner(depth_factor):
        def run():
            floor = 0.060 if rng.random() < 0.5 else 0.105
            return sig * depth_factor + floor + rng.normal(0, 1e-4)
        return run

    for _ in range(5):
        est = bench._diff_time(runner(1), runner(2), trials=9)
        assert abs(est - sig) < 0.004, est


def test_diff_time_raises_when_all_rounds_invert(bench):
    # a 2K-deep run can never legitimately be faster than a K-deep one;
    # persistent inversion means outages corrupted every round
    with pytest.raises(RuntimeError, match="outages"):
        bench._diff_time(lambda: 0.5, lambda: 0.4, trials=3)


def test_regressions_vs_prior(bench, tmp_path, monkeypatch):
    """>30% drops against the BEST prior value surface; improvements and
    small dips don't; the ucidigits rename maps old files forward; prior
    headline values only compare when the metric name matches."""
    priors = {
        "BENCH_r01.json": {"metric": "resnet50_train_samples_per_sec_per_chip",
                           "value": 2000.0, "lenet_samples_per_sec": 50000.0,
                           "mnist_real_test_acc": 0.95},
        "BENCH_r02.json": {"metric": "lenet_mnist_train_samples_per_sec_per_chip",
                           "value": 99999.0, "flash_speedup": 2.0},
    }
    for name, d in priors.items():
        (tmp_path / name).write_text(json.dumps(d))
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))

    current = {"metric": "resnet50_train_samples_per_sec_per_chip",
               "value": 1900.0,              # small dip: not flagged
               "lenet_samples_per_sec": 20000.0,   # 60% drop: flagged
               "ucidigits_test_acc": 0.5,          # vs renamed 0.95: flagged
               "flash_speedup": 2.5}               # improvement: not flagged
    regs = {r["metric"]: r for r in bench._regressions_vs_prior(current)}
    assert set(regs) == {"lenet_samples_per_sec", "ucidigits_test_acc"}
    assert regs["lenet_samples_per_sec"]["best_prior"] == 50000.0
    # r02's headline (99999 under a DIFFERENT metric) must not poison the
    # resnet "value" comparison
    assert "value" not in regs


def test_regressions_empty_without_priors(bench, tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    assert bench._regressions_vs_prior({"metric": "m", "value": 1.0}) == []


def test_diff_time_resamples_through_relay_outage(bench):
    """A multi-second outage covering one sample group makes the round
    violate the diff <= 0.55*min(t_2K) invariant — the estimator must
    detect it and resample instead of publishing a 27x-off number (the
    observed failure this guard exists for)."""
    sig, floor = 0.020, 0.060
    state = {"i": 0}

    def run_k():
        state["i"] += 1
        # round 1: fine for K-runs
        return sig + floor

    def run_2k():
        state["i"] += 1
        if state["i"] <= 10:          # every 2K-sample of round 1: outage
            return 2 * sig + floor + 11.0
        return 2 * sig + floor

    est = bench._diff_time(run_k, run_2k, trials=5)
    assert abs(est - sig) < 1e-6
