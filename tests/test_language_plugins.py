"""Language plugin tests (reference: deeplearning4j-nlp-japanese /
deeplearning4j-nlp-korean / deeplearning4j-nlp-uima test suites)."""
import numpy as np

from deeplearning4j_tpu.nlp.tokenization.japanese import (JapaneseTokenizer,
                                                          JapaneseTokenizerFactory,
                                                          segment as ja_segment)
from deeplearning4j_tpu.nlp.tokenization.korean import (KoreanTokenizerFactory,
                                                        segment as ko_segment)
from deeplearning4j_tpu.nlp.annotators import (Annotation, AnnotatorPipeline,
                                               SentenceAnnotator,
                                               TokenizerAnnotator,
                                               StemmerAnnotator, PoStagger)


# ------------------------------------------------------------------ Japanese

def test_japanese_segmentation_basic():
    toks = ja_segment("私は東京大学の学生です。")
    assert toks == ["私", "は", "東京", "大学", "の", "学生", "です", "。"]


def test_japanese_katakana_and_unknown_words():
    # katakana loanwords stay whole even when absent from the lexicon
    toks = ja_segment("データサイエンスを勉強します")
    assert "を" in toks
    assert "勉強" in toks
    joined = "".join(toks)
    assert joined == "データサイエンスを勉強します"
    kat = [t for t in toks if all(0x30A0 <= ord(c) <= 0x30FF or c == "ー"
                                  for c in t)]
    assert any(len(t) >= 4 for t in kat), f"katakana run split: {toks}"


def test_japanese_compound_dictionary_preference():
    # 自然言語処理 is one lexicon entry and must beat char-by-char splits
    toks = ja_segment("自然言語処理の研究")
    assert toks == ["自然言語処理", "の", "研究"]


def test_japanese_tokenizer_factory_spi():
    f = JapaneseTokenizerFactory()
    t = f.create("私は日本語を話します")
    toks = t.get_tokens()
    assert toks[0] == "私" and "日本語" in toks
    # Tokenizer iteration contract (iteration consumes; compare fresh)
    t2 = f.create("今日は良い")
    seen = []
    while t2.has_more_tokens():
        seen.append(t2.next_token())
    assert seen == f.create("今日は良い").get_tokens()


def test_japanese_word2vec_end_to_end():
    """Word2Vec trains over Japanese text through the plugin factory
    (VERDICT r2 item 9 'done' bar)."""
    from deeplearning4j_tpu.nlp import Word2Vec
    from deeplearning4j_tpu.nlp.text import CollectionSentenceIterator
    sentences = [
        "私は日本語を勉強します",
        "彼は東京の大学で研究します",
        "私は東京が好きです",
        "彼女は日本語の本を読みます",
        "学生は大学で勉強します",
        "私は映画が好きです",
    ] * 10
    w2v = (Word2Vec.builder()
           .min_word_frequency(1).layer_size(16).seed(7).epochs(2)
           .window_size(3)
           .iterate(CollectionSentenceIterator(sentences))
           .tokenizer_factory(JapaneseTokenizerFactory())
           .build())
    w2v.fit()
    assert w2v.has_word("日本語") and w2v.has_word("大学")
    v = w2v.get_word_vector("日本語")
    assert np.asarray(v).shape == (16,) and np.isfinite(v).all()
    sims = w2v.words_nearest("勉強", 3)
    assert len(sims) == 3


# ------------------------------------------------------------------- Korean

def test_korean_josa_separation():
    assert ko_segment("학생이 학교에 갑니다") == \
        ["학생", "이", "학교", "에", "갑니다"]
    # phonotactics: 는 after open syllable, 은 after closed
    assert ko_segment("나는 책을 읽습니다") == ["나", "는", "책", "을", "읽습니다"]


def test_korean_mixed_script():
    toks = ko_segment("AI는 2024년에 발전했다.")
    assert toks[0] == "AI" and "는" in toks and "2024" in toks
    assert toks[-1] == "."


def test_korean_factory_spi():
    f = KoreanTokenizerFactory()
    assert f.create("한국어를 공부합니다").get_tokens() == \
        ["한국어", "를", "공부합니다"]


# ---------------------------------------------------------------- annotators

def test_annotator_pipeline_sentences_tokens_stems_pos():
    pipe = AnnotatorPipeline(SentenceAnnotator(), TokenizerAnnotator(),
                             StemmerAnnotator(), PoStagger())
    ann = pipe.process("Dr. Smith studied the models. They were training "
                       "quickly! Results improved.")
    sents = ann.select("sentence")
    assert len(sents) == 3  # "Dr." must not split a sentence
    assert sents[0].text.startswith("Dr. Smith")
    toks = ann.select("token")
    by_text = {t.text: t for t in toks}
    assert by_text["studied"].attrs["stem"] == "studi"
    assert by_text["models"].attrs["stem"] == "model"
    assert by_text["the"].attrs["pos"] == "DT"
    assert by_text["They"].attrs["pos"] == "PRP"
    assert by_text["training"].attrs["pos"] == "VBG"
    assert by_text["quickly"].attrs["pos"] == "RB"
    assert by_text["Smith"].attrs["pos"] == "NNP"
    # spans point back into the document
    t = by_text["models"]
    assert ann.text[t.begin:t.end] == "models"


def test_sentence_annotator_decimal_and_tail():
    ann = SentenceAnnotator().process(Annotation("Pi is 3.14 roughly. Yes"))
    sents = [s.text for s in ann.select("sentence")]
    assert sents == ["Pi is 3.14 roughly.", "Yes"]


# --------------------------------------------------- P8 sharded word2vec

def test_spmd_word2vec_matches_single_device():
    """Sharded pair-stream training must produce (numerically) the same
    embeddings as single-device training — the all-reduce IS the reference's
    parameter averaging at window 1 (P8, spark word2vec)."""
    import jax
    from deeplearning4j_tpu.parallel.sharding import make_mesh
    from deeplearning4j_tpu.parallel.word2vec import SpmdWord2Vec
    from deeplearning4j_tpu.nlp import Word2Vec

    sentences = ["the quick brown fox jumps over the lazy dog",
                 "the dog sleeps in the sun",
                 "a fox is a wild animal",
                 "the sun is bright today"] * 8
    kw = dict(layer_size=16, min_word_frequency=1, seed=3, epochs=2, window=2)
    a = Word2Vec(**kw)
    a.fit(sentences)
    b = SpmdWord2Vec(mesh=make_mesh(n_data=8), **kw)
    b.fit(sentences)
    va = a.lookup_table.syn0
    vb = b.lookup_table.syn0
    np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                               rtol=1e-4, atol=1e-5)


def test_spmd_word2vec_sharded_tables():
    """Row-sharded embedding tables over the model axis (vocab too large for
    one chip) still train and answer nearest-neighbor queries."""
    from deeplearning4j_tpu.parallel.sharding import make_mesh
    from deeplearning4j_tpu.parallel.word2vec import SpmdWord2Vec

    sentences = ["alpha beta gamma delta epsilon zeta eta theta"] * 12
    w = SpmdWord2Vec(mesh=make_mesh(n_data=4, n_model=2), shard_tables=True,
                     layer_size=8, min_word_frequency=1, seed=1, epochs=2)
    w.fit(sentences)
    assert w.has_word("alpha")
    assert len(w.words_nearest("beta", 3)) == 3


def test_spmd_word2vec_sharded_tables_parity_with_replicated():
    """VERDICT r3 #7: the ROW-SHARDED path must produce the same embeddings
    as replicated training — a wrong scatter over the model axis would pass
    the trains-and-answers-queries test above but not this one."""
    from deeplearning4j_tpu.parallel.sharding import make_mesh
    from deeplearning4j_tpu.parallel.word2vec import SpmdWord2Vec

    sentences = ["the quick brown fox jumps over the lazy dog",
                 "the dog sleeps in the sun",
                 "a fox is a wild animal",
                 "the sun is bright today"] * 8
    kw = dict(layer_size=16, min_word_frequency=1, seed=3, epochs=2, window=2)
    import jax
    repl = SpmdWord2Vec(mesh=make_mesh(n_data=4,
                                       devices=jax.devices()[:4]), **kw)
    repl.fit(sentences)
    shard = SpmdWord2Vec(mesh=make_mesh(n_data=4, n_model=2),
                         shard_tables=True, **kw)
    shard.fit(sentences)
    n = np.asarray(repl.lookup_table.syn0).shape[0]
    # the sharded table pads the vocab to tile the model axis; real rows
    # must match the replicated run exactly (same pair stream, same seed)
    np.testing.assert_allclose(np.asarray(shard.lookup_table.syn0)[:n],
                               np.asarray(repl.lookup_table.syn0),
                               rtol=1e-4, atol=1e-5)
