"""steps_per_execution: K train steps compiled into one executable
(nn/multistep.py) must be SEMANTICALLY IDENTICAL to K fit_batch calls —
same rng chain, same per-layer state threading, same scores — with
listeners firing on the documented K-step cadence, and graceful per-batch
fallback whenever a group can't scan."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, BatchNormalization,
                                MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd, Adam)
from deeplearning4j_tpu.optimize.listeners import IterationListener


def _mk_net(seed=5, dropout=None, bn=False, tbptt=False):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2)).list()
    b = b.layer(DenseLayer(n_out=16, activation="tanh",
                           dropout=dropout))
    if bn:
        b = b.layer(BatchNormalization())
    b = b.layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
    conf = b.input_type(InputType.feed_forward(8)).build()
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)]
        out.append(DataSet(x, y))
    return out


@pytest.mark.parametrize("dropout,bn", [(None, False), (0.3, False),
                                        (None, True)])
def test_multi_step_matches_per_batch(dropout, bn):
    """K-step scan == K singles: params, BN running state, and the rng
    chain (dropout masks) all line up."""
    sets = _batches(8)
    a = _mk_net(dropout=dropout, bn=bn)
    b = _mk_net(dropout=dropout, bn=bn)
    a.fit(ListDataSetIterator(sets))
    b.fit(ListDataSetIterator(sets), steps_per_execution=4)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    for sa, sb in zip(jax.tree_util.tree_leaves(a.states),
                      jax.tree_util.tree_leaves(b.states)):
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                                   rtol=1e-5, atol=1e-6)
    assert a.iteration_count == b.iteration_count == 8
    # per-step scores surface from the scan
    assert b.last_scores.shape == (4,)
    assert np.isclose(float(b.last_scores[-1]), b.score_value)


def test_multi_step_listener_cadence_and_ragged_tail():
    """10 batches at K=4: two scanned groups fire listeners at iterations 4
    and 8; the ragged tail of 2 runs per-batch at 9 and 10."""
    seen = []

    class Recorder(IterationListener):
        def iteration_done(self, model, iteration):
            seen.append(iteration)

    net = _mk_net()
    net.set_listeners(Recorder())
    net.fit(ListDataSetIterator(_batches(10)), steps_per_execution=4)
    assert seen == [4, 8, 9, 10]
    assert net.iteration_count == 10


def test_multi_step_mixed_mask_group_falls_back():
    """A group mixing masked and unmasked batches can't stack into one scan
    pytree — it must quietly run per-batch and still train correctly."""
    from deeplearning4j_tpu import RnnOutputLayer, GravesLSTM
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1)).list()
            .layer(GravesLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="MCXENT"))
            .input_type(InputType.recurrent(4)).build())
    rng = np.random.default_rng(1)
    sets = []
    for i in range(4):
        x = rng.normal(size=(2, 6, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 6))]
        m = np.ones((2, 6), np.float32) if i % 2 else None
        sets.append(DataSet(x, y, features_mask=m, labels_mask=m))
    a = MultiLayerNetwork(conf).init()
    b = MultiLayerNetwork(conf).init()
    a.fit(ListDataSetIterator(sets))
    b.fit(ListDataSetIterator(sets), steps_per_execution=4)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-6, atol=1e-7)
    assert b.iteration_count == 4


def _tbptt_conf(T_unused=None):
    from deeplearning4j_tpu import RnnOutputLayer, GravesLSTM
    return (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
            .list()
            .layer(GravesLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="MCXENT"))
            .input_type(InputType.recurrent(4))
            .backprop_type("tbptt").tbptt_fwd_length(4).tbptt_back_length(4)
            .build())


def _tbptt_sets(T, n=4, seed=2):
    rng = np.random.default_rng(seed)
    sets = []
    for _ in range(n):
        x = rng.normal(size=(2, T, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, T))]
        sets.append(DataSet(x, y))
    return sets


def test_multi_step_tbptt_scans_with_parity():
    """TBPTT batches whose windows tile the sequence scan too: K batches x W
    windows flatten into one executable with carry resets at batch
    boundaries and a replayed rng table — params, carried-state semantics,
    and the window-mean scores all match per-batch TBPTT."""
    seen = []

    class Recorder(IterationListener):
        def iteration_done(self, model, iteration):
            seen.append(iteration)

    sets = _tbptt_sets(T=12)   # W = 3 windows of L=4
    a = MultiLayerNetwork(_tbptt_conf()).init()
    b = MultiLayerNetwork(_tbptt_conf()).init()
    b.set_listeners(Recorder())
    a.fit(ListDataSetIterator(sets))
    b.fit(ListDataSetIterator(sets), steps_per_execution=2)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-6, atol=1e-7)
    assert seen == [2, 4]          # K-step cadence, 2 groups of K=2
    assert b.last_scores.shape == (2,)
    # per-batch score = mean over that batch's windows == singles' score
    a2 = MultiLayerNetwork(_tbptt_conf()).init()
    for ds in sets:
        a2.fit_batch(ds)
    np.testing.assert_allclose(float(b.last_scores[-1]), a2.score_value,
                               rtol=1e-5)


def test_multi_step_tbptt_ragged_windows_fall_back():
    """T=10 does not tile into L=4 windows: the group must quietly run
    per-batch TBPTT and still match plain fit."""
    sets = _tbptt_sets(T=10, seed=3)
    a = MultiLayerNetwork(_tbptt_conf()).init()
    b = MultiLayerNetwork(_tbptt_conf()).init()
    a.fit(ListDataSetIterator(sets))
    b.fit(ListDataSetIterator(sets), steps_per_execution=2)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-6, atol=1e-7)


def test_multi_step_computation_graph_parity():
    """ComputationGraph shares the mixin: scanned groups == singles."""
    from deeplearning4j_tpu import ComputationGraph

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="MCXENT"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8)).build())
        return ComputationGraph(conf).init()

    sets = _batches(6)
    a, b = build(), build()
    a.fit(ListDataSetIterator(sets))
    b.fit(ListDataSetIterator(sets), steps_per_execution=3)
    for pa, pb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=1e-5, atol=1e-6)
    assert a.iteration_count == b.iteration_count == 6
    assert b.last_scores.shape == (3,)


def test_prepare_steps_reusable_executable():
    """prepare_steps + fit_prepared: the bench hot path — one prepared stack
    can run repeatedly (inputs are NOT donated) and each run advances
    training by K steps."""
    net = _mk_net()
    sets = _batches(4)
    prepared = net.prepare_steps(sets)
    assert prepared is not None
    s0 = None
    for i in range(3):
        net.fit_prepared(prepared)
        if i == 0:
            s0 = float(net.last_scores[-1])
    assert net.iteration_count == 12
    assert float(net.last_scores[-1]) < s0


def test_sharded_trainer_steps_per_execution_parity():
    """K sharded steps inside one scanned executable (collectives inside the
    scan) must equal K per-batch sharded steps AND K single-device steps —
    the multi-chip hot path loses its per-step host dispatch without
    changing semantics."""
    from deeplearning4j_tpu.parallel.sharding import ShardedTrainer, make_mesh

    sets = _batches(8, batch=32, seed=4)
    single = _mk_net()
    for ds in sets:
        single.fit_batch(ds)

    sharded_1 = _mk_net()
    tr1 = ShardedTrainer(sharded_1, mesh=make_mesh(n_data=8))
    tr1.fit(ListDataSetIterator(sets))
    np.testing.assert_allclose(single.get_flat_params(),
                               sharded_1.get_flat_params(),
                               rtol=1e-5, atol=1e-6)

    sharded_k = _mk_net()
    trk = ShardedTrainer(sharded_k, mesh=make_mesh(n_data=8))
    trk.fit(ListDataSetIterator(sets), steps_per_execution=4)
    np.testing.assert_allclose(single.get_flat_params(),
                               sharded_k.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    assert sharded_k.iteration_count == 8
    assert sharded_k.last_scores.shape == (4,)


def test_sharded_trainer_grouped_padding_falls_back():
    """A group containing a batch that needs wrap-padding (not divisible by
    the data axis) must quietly run per-batch — no example dropped, params
    still match the single-device run."""
    from deeplearning4j_tpu.parallel.sharding import ShardedTrainer, make_mesh

    sets = _batches(4, batch=32, seed=5)
    odd = _batches(1, batch=27, seed=6)  # 27 % 8 != 0
    mixed = sets[:2] + odd + sets[2:]
    single = _mk_net()
    for ds in mixed:
        single.fit_batch(ds)
    sharded = _mk_net()
    tr = ShardedTrainer(sharded, mesh=make_mesh(n_data=8))
    tr.fit(ListDataSetIterator(mixed), steps_per_execution=5)
    np.testing.assert_allclose(single.get_flat_params(),
                               sharded.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    assert sharded.examples_fit == 32 * 4 + 27


def test_lstm_tbptt_carry_donation_no_warnings_both_paths():
    """ISSUE-7 satellite: the char_rnn/LSTM TBPTT carries must donate
    cleanly on BOTH training paths — the scanned multi_tbptt executable
    (fixed in PR 6: final carries are scan outputs) and the per-window
    fit_batch path (carries are donate_argnums=8 of the tbptt train step).
    JAX computes donation aliasing platform-independently at lowering, so
    this CPU test catches a donated-but-unusable carry buffer exactly like
    the TPU run that put "Some donated buffers were not usable:
    float32[64,256] x4" in BENCH_r05's tail; bench.py now also counts the
    warning across every workload (donation_warnings)."""
    import warnings
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm

    def mk():
        net = char_rnn_lstm(vocab_size=12, hidden=16, layers=2, tbptt=5)
        return net.init()

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(4, 21))
    x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        per_window = mk()
        per_window.fit_batch(ds)             # per-window tbptt train step
        per_window.fit_batch(ds)
        scanned = mk()
        plan = scanned.prepare_steps([ds] * 2)
        assert plan is not None and plan[0] == "tbptt"
        scanned.fit_prepared(plan)           # scanned multi_tbptt executable
        scanned.fit_prepared(plan)
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    # both paths still train to finite scores
    assert np.isfinite(float(per_window.score_value))
    assert np.isfinite(float(scanned.score_value))


def test_char_rnn_bench_call_sequence_donation_clean():
    """ISSUE-9 satellite: the EXACT call sequence bench.py's char-RNN
    workload drives (`_scanned_fit_step_s`: an eligibility-probe
    prepare_steps, then K- and 2K-deep plans each fit_prepared twice,
    interleaved) must lower with zero "Some donated buffers were not
    usable" warnings — the BENCH_r05 tail's float32[64,256]x4 came from
    this path's carries before they became scan outputs. Donation aliasing
    is computed platform-independently at lowering, so the CPU run guards
    the TPU bench."""
    import warnings
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm

    net = char_rnn_lstm(vocab_size=12, hidden=16, layers=2, tbptt=5).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(8, 21))
    x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        plan = net.prepare_steps([ds] * 2)         # bench eligibility probe
        assert plan is not None and plan[0] == "tbptt"
        K = 3
        p1 = net.prepare_steps([ds] * K)
        p2 = net.prepare_steps([ds] * (2 * K))
        net.fit_prepared(p1)                       # compile + warm both
        net.fit_prepared(p2)
        net.fit_prepared(p1)                       # timed-loop re-runs
        net.fit_prepared(p2)
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    assert np.isfinite(float(net.score_value))


def test_bench_r05_exact_geometry_donation_clean():
    """ISSUE-15 satellite: the BENCH_r05 tail's warning named EXACTLY
    `float32[64,256] x4` — the char-RNN bench geometry (batch 64, hidden
    256, 2 LSTM layers x (h, c) carries). The small-geometry tests above
    guard the code path; this one pins the literal buffer shapes from the
    bench record, so a donation regression reproduces the historical
    warning VERBATIM and can never be dismissed as a different workload.
    The hunt re-ran every [64,256]-shaped candidate (scanned TBPTT,
    per-window TBPTT, generate, rnn_time_step) — all lower clean; the
    original emitter was the pre-PR-6/7 TBPTT carries. bench.py's warning
    net (donation_warnings + regressions entry) stays the run-time
    backstop across every workload."""
    import warnings
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm

    net = char_rnn_lstm(vocab_size=20, hidden=256, layers=2, tbptt=5).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 20, size=(64, 11))    # batch 64 -> [64,256] carries
    x = np.eye(20, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(20, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        net.fit_batch(ds)                      # per-window tbptt path
        plan = net.prepare_steps([ds] * 2)     # scanned multi_tbptt path
        assert plan is not None and plan[0] == "tbptt"
        net.fit_prepared(plan)
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    # the historical shape string must appear in NO warning of any kind
    offender = [str(w.message) for w in caught if "64,256" in str(w.message)]
    assert offender == [], offender
    assert np.isfinite(float(net.score_value))
