"""Unified telemetry subsystem tests: structured tracing (span nesting,
cross-thread propagation, Chrome-trace export round-trip), the central
MetricsRegistry (counters/gauges/histograms, exact-bucket percentiles),
Prometheus text exposition, XLA compile accounting, the deterministic
time_source clock, listener coverage (PerformanceListener, ProfilerListener
with a mocked profiler, TelemetryListener), and the serving/UI scrape +
trace endpoints (acceptance criteria)."""
import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.telemetry import (CompileTracker, MetricsRegistry,
                                          TelemetryListener, Tracer,
                                          get_registry, render_prometheus)
from deeplearning4j_tpu.telemetry.trace import NOOP_SPAN, current_span
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider,
                                                 monotonic_s, now_ms)


@pytest.fixture
def manual_clock():
    clock = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(clock)
    try:
        yield clock
    finally:
        TimeSourceProvider.reset()


# ------------------------------------------------------------------ tracing

def test_span_nesting_parent_ids_and_attributes():
    t = Tracer()
    with t.span("root", kind="test") as root:
        assert current_span() is root
        with t.span("child") as child:
            assert child.parent_id == root.span_id
            assert child.trace_id == root.trace_id
            with t.span("grandchild") as g:
                assert g.parent_id == child.span_id
        assert current_span() is root
    assert current_span() is None
    assert root.duration_ms is not None
    assert root.attributes["kind"] == "test"


def test_spans_on_different_threads_do_not_nest_implicitly():
    t = Tracer()
    seen = {}

    def worker():
        seen["span"] = current_span()

    with t.span("root"):
        th = threading.Thread(target=worker)
        th.start()
        th.join()
    assert seen["span"] is None     # thread-local, not process-global


def test_explicit_parent_propagates_across_threads():
    t = Tracer()
    with t.span("request") as root:
        ctx = t.current()

    def consumer():
        s = t.start_span("dispatch", parent=ctx)
        s.end()
        return s

    th_result = []
    th = threading.Thread(target=lambda: th_result.append(consumer()))
    th.start()
    th.join()
    assert th_result[0].parent_id == root.span_id


def test_record_span_retroactive(manual_clock):
    t = Tracer()
    t0 = monotonic_s()
    manual_clock.advance(0.25)
    s = t.record_span("queued", t0, monotonic_s())
    assert s.duration_ms == pytest.approx(250.0)


def test_chrome_trace_export_round_trip():
    t = Tracer()
    with t.span("a"):
        with t.span("b"):
            with t.span("c"):
                pass
    text = json.dumps(t.to_chrome_trace())
    trace = json.loads(text)                    # valid JSON
    ev = trace["traceEvents"]
    assert len(ev) == 3
    by_id = {e["args"]["span_id"]: e for e in ev}
    c = next(e for e in ev if e["name"] == "c")
    b = by_id[c["args"]["parent_id"]]
    a = by_id[b["args"]["parent_id"]]
    assert (a["name"], b["name"]) == ("a", "b")
    assert a["args"]["parent_id"] is None
    for e in ev:
        assert e["ph"] == "X" and e["dur"] >= 0


def test_tracer_export_to_file(tmp_path):
    t = Tracer()
    with t.span("only"):
        pass
    p = t.export(tmp_path / "trace.json")
    assert json.loads(open(p).read())["traceEvents"][0]["name"] == "only"


def test_disabled_tracer_is_noop_and_cheap():
    t = Tracer(enabled=False)
    s = t.span("x")
    assert s is NOOP_SPAN
    with s:
        assert current_span() is None
    assert t.finished_spans() == []
    assert t.record_span("y", 0, 1) is NOOP_SPAN


def test_tracer_ring_buffer_bounded():
    t = Tracer(max_spans=4)
    for i in range(10):
        with t.span(f"s{i}"):
            pass
    spans = t.finished_spans()
    assert len(spans) == 4
    assert [s.name for s in spans] == ["s6", "s7", "s8", "s9"]
    assert t.dropped == 6


# ----------------------------------------------------------------- registry

def test_counter_labels_and_atomiccounter_compat():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "help")
    c.add(3)                      # AtomicCounter spelling
    c.inc(2, bucket="8")
    assert c.get() == 5           # unlabeled read sums all series
    assert c.get(bucket="8") == 2
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create is idempotent; a kind clash raises
    assert reg.counter("reqs_total") is c
    with pytest.raises(TypeError):
        reg.gauge("reqs_total")


def test_gauge_set_and_callback():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(4)
    assert g.get() == 4
    cb = reg.gauge("cb_depth", fn=lambda: 7.0)
    assert cb.get() == 7.0
    broken = reg.gauge("broken", fn=lambda: 1 / 0)
    assert broken.get() is None
    assert broken.series() == []  # dead callback must not kill a scrape


def test_histogram_exact_percentiles_and_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms", buckets=(1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    assert h.count() == 4
    assert h.sum() == pytest.approx(555.5)
    assert h.percentile(0.0) == 0.5
    assert h.percentile(1.0) == 500
    ((labels, data),) = h.series()
    assert labels == {}
    assert data["buckets"] == [(1.0, 1), (10.0, 2), (100.0, 3),
                               (float("inf"), 4)]   # cumulative
    p = h.percentiles()
    assert p["count"] == 4 and p["max"] == 500


def test_histogram_reservoir_bounded_most_recent():
    reg = MetricsRegistry()
    h = reg.histogram("r_ms")
    h.reservoir_cap = h.RESERVOIR
    for v in range(h.RESERVOIR + 100):
        h.observe(float(v))
    assert h.count() == h.RESERVOIR + 100      # total count is unbounded
    assert h.percentile(0.0) == 100.0          # oldest 100 evicted


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("a_total").inc(1)
    reg.counter("b_total").inc(2, k="v")
    reg.gauge("g").set(3)
    reg.histogram("h").observe(10)
    snap = reg.snapshot()
    assert snap["a_total"] == 1
    assert snap["b_total"] == {"k=v": 2}
    assert snap["g"] == 3.0
    assert snap["h"]["count"] == 1 and snap["h"]["p50"] == 10.0
    json.dumps(snap)               # JSON-serializable end to end


# --------------------------------------------------------------- prometheus

def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", 'served "ok"\nrequests')
    c.inc(5)
    c.inc(2, route="/predict", code="200")
    reg.gauge("queue_depth", fn=lambda: 3)
    h = reg.histogram("latency_ms", buckets=(10, 100))
    h.observe(7)
    h.observe(70)
    text = render_prometheus(reg)
    lines = text.splitlines()
    # OpenMetrics: counter FAMILY drops the _total suffix, samples keep it
    assert "# TYPE requests counter" in lines
    assert '# HELP requests served "ok"\\nrequests' in lines
    assert "requests_total 5" in lines
    assert 'requests_total{code="200",route="/predict"} 2' in lines
    assert "# TYPE queue_depth gauge" in lines and "queue_depth 3" in lines
    assert 'latency_ms_bucket{le="10"} 1' in lines
    assert 'latency_ms_bucket{le="100"} 2' in lines
    assert 'latency_ms_bucket{le="+Inf"} 2' in lines
    assert "latency_ms_sum 77" in lines
    assert "latency_ms_count 2" in lines
    assert lines[-1] == "# EOF" and text.endswith("\n")


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.counter("x_total").inc(1, path='a"b\\c')
    text = render_prometheus(reg)
    assert 'x_total{path="a\\"b\\\\c"} 1' in text


# -------------------------------------------------------------- time source

def test_manual_clock_drives_wall_and_monotonic(manual_clock):
    t0_wall, t0_mono = now_ms(), monotonic_s()
    manual_clock.advance(2.5)
    assert now_ms() - t0_wall == 2500
    assert monotonic_s() - t0_mono == pytest.approx(2.5)


def test_stats_reports_use_time_source(manual_clock):
    from deeplearning4j_tpu.ui.stats import ServingStatsReport
    r = ServingStatsReport("s", {"requests": 1})
    assert r.data["time"] == pytest.approx(1000.0)


# ------------------------------------------------------- compile accounting

def test_compile_tracker_counts_and_by_bucket():
    reg = MetricsRegistry()
    ct = CompileTracker(reg)
    ct.record(100.0, bucket=4, phase="serve")
    ct.record(50.0, bucket=8, phase="serve")
    ct.record(25.0, bucket=8, phase="warmup")
    assert ct.total() == 3
    assert ct.total_ms() == pytest.approx(175.0)
    text = render_prometheus(reg)
    assert 'compiles_total{bucket="8",phase="serve"} 1' in text
    assert "compile_ms_total 175" in text


def test_timed_first_call_records_once_and_delegates_attrs():
    reg = MetricsRegistry()
    from deeplearning4j_tpu.telemetry.xla import timed_first_call
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2
    fn.custom_attr = "yes"
    wrapped = timed_first_call(fn, "unit", registry=reg)
    assert wrapped(3) == 6 and wrapped(4) == 8
    assert wrapped.custom_attr == "yes"        # attribute pass-through
    assert reg.counter("jit_compiles_total").get() == 1
    assert reg.counter("jit_compiles_total").get(fn="unit") == 1


# ---------------------------------------------------------------- listeners

class _Model:
    score_value = 0.5
    params = None


def test_performance_listener_deterministic_with_manual_clock(manual_clock):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener
    reg = MetricsRegistry()
    logs = []
    pl = PerformanceListener(frequency=1, log_fn=logs.append, registry=reg)
    m = _Model()
    pl.record_batch_size(32)
    pl.iteration_done(m, 1)                # primes the clock
    pl.record_batch_size(32)
    manual_clock.advance(0.5)
    pl.iteration_done(m, 2)
    assert pl.last_iteration_ms == pytest.approx(500.0)
    assert pl.last_batches_per_sec == pytest.approx(2.0)
    # the priming iteration does not reset _samples_since, so the first
    # measured window covers both recorded batches (64 rows / 0.5 s)
    assert pl.last_samples_per_sec == pytest.approx(128.0)
    assert logs and "500.00 ms/iter" in logs[0]
    assert reg.counter("training_samples_total").get() == 64
    assert reg.histogram("training_iteration_ms").count() == 1
    assert reg.gauge("training_samples_per_sec").get() == pytest.approx(128.0)


class _FakeProfiler:
    def __init__(self):
        self.starts = 0
        self.stops = 0

    def start_trace(self, log_dir):
        self.starts += 1

    def stop_trace(self):
        self.stops += 1


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


def test_profiler_listener_normal_window(fake_profiler, tmp_path):
    from deeplearning4j_tpu.ui.stats import ProfilerListener
    pl = ProfilerListener(tmp_path, start_iteration=2, n_iterations=2)
    m = _Model()
    for i in range(1, 6):
        pl.iteration_done(m, i)
    assert fake_profiler.starts == 1 and fake_profiler.stops == 1
    pl.close()                               # idempotent: window already shut
    assert fake_profiler.stops == 1


def test_profiler_listener_no_leak_when_training_ends_early(fake_profiler,
                                                           tmp_path):
    """Regression: training that ends inside the trace window used to leak
    an active jax.profiler trace; epoch end (and close()) must stop it."""
    from deeplearning4j_tpu.ui.stats import ProfilerListener
    pl = ProfilerListener(tmp_path, start_iteration=1, n_iterations=100)
    m = _Model()
    pl.iteration_done(m, 1)                  # trace starts, window never ends
    assert fake_profiler.starts == 1 and fake_profiler.stops == 0
    pl.on_epoch_end(m)                       # last reliable hook
    assert fake_profiler.stops == 1
    assert not pl._active
    pl.close()
    assert fake_profiler.stops == 1          # close() after stop is a no-op


def test_telemetry_listener_flushes_registry_into_router():
    from deeplearning4j_tpu.ui.storage import CollectionStatsStorageRouter
    reg = MetricsRegistry()
    router = CollectionStatsStorageRouter()
    tl = TelemetryListener(router=router, registry=reg, frequency=2,
                           session_id="tele")
    m = _Model()
    for i in range(1, 5):
        tl.iteration_done(m, i)
    assert reg.counter("training_iterations_total").get() == 4
    assert len(router.updates) == 2          # every 2nd iteration
    d = router.updates[-1].data
    assert d["type"] == "telemetry" and d["session_id"] == "tele"
    assert d["metrics"]["training_iterations_total"] == 4


def test_telemetry_listener_tolerates_broken_router():
    class Broken:
        def put_update(self, r):
            raise RuntimeError("down")
    tl = TelemetryListener(router=Broken(), registry=MetricsRegistry(),
                           frequency=1)
    tl.iteration_done(_Model(), 1)           # must not raise


def test_stats_listener_mirrors_into_registry():
    from deeplearning4j_tpu.ui.stats import StatsListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    reg = MetricsRegistry()
    sl = StatsListener(InMemoryStatsStorage(), session_id="s",
                       collect_params=False, collect_gradients=False,
                       collect_memory=False, registry=reg)

    class M(_Model):
        def param_table(self):
            return {}

        def num_params(self):
            return 0
    for i in range(1, 4):
        sl.iteration_done(M(), i)
    assert reg.histogram("training_iteration_ms").count() == 2
    assert reg.gauge("training_score").get() == pytest.approx(0.5)


# --------------------------------------------------- serving metrics compat

def test_serving_metrics_snapshot_backcompat_and_prometheus():
    from deeplearning4j_tpu.serving import ServingMetrics
    sm = ServingMetrics()
    sm.record_batch(4, n_requests=2, n_rows=3)
    sm.record_latency(5.0)
    sm.record_latency(15.0)
    snap = sm.snapshot(queue_depth=1)
    assert snap["requests"] == 2 and snap["rows"] == 3
    assert snap["batches"] == 1
    assert snap["batch_size_histogram"] == {"4": 1}
    assert snap["latency_ms"]["count"] == 2
    assert snap["latency_ms"]["p50"] == 5.0
    text = sm.to_prometheus()
    assert "requests_total 2" in text
    assert 'batch_size_total{bucket="4"} 1' in text
    assert "latency_ms_count 2" in text


# ------------------------------------------------- acceptance: live serving

class StubModel:
    def output(self, x):
        return np.asarray(x) * 2.0


def test_serving_prometheus_scrape_and_span_tree_acceptance():
    """Acceptance: GET /metrics?format=prometheus on a live ServingServer
    returns valid exposition text including requests_total, the latency_ms
    histogram, compiles_total, and the queue-depth gauge; a traced /predict
    yields a predict->admission span tree plus a batch span (own trace)
    LINKED to the request — exported as valid Chrome-trace JSON with
    flow events connecting request and batch lanes."""
    from deeplearning4j_tpu.serving import ServingServer
    server = ServingServer(StubModel(), port=0).start()
    try:
        for rows in (1, 3, 2):
            x = np.ones((rows, 4), dtype=np.float32)
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": x.tolist()}).encode())
            with urllib.request.urlopen(req, timeout=30) as r:
                json.loads(r.read())

        with urllib.request.urlopen(server.url + "/metrics?format=prometheus",
                                    timeout=30) as r:
            # exemplars ride the exposition, so it must declare (and be)
            # OpenMetrics — the classic text/plain parser rejects them
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            text = r.read().decode()
        assert "requests_total 3" in text
        assert "latency_ms_bucket" in text and "latency_ms_count 3" in text
        assert "compiles_total" in text
        assert "queue_depth 0" in text
        # JSON stays the default for back-compat
        with urllib.request.urlopen(server.url + "/metrics", timeout=30) as r:
            snap = json.loads(r.read())
        assert snap["requests"] == 3 and snap["compiles"] >= 2

        with urllib.request.urlopen(server.url + "/trace", timeout=30) as r:
            trace = json.loads(r.read())        # valid JSON
        ev = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        by_id = {e["args"]["span_id"]: e for e in ev}
        chains = 0
        for e in ev:
            if e["name"] != "dispatch":
                continue
            batch = by_id.get(e["args"]["parent_id"])
            assert batch is not None and batch["name"] == "batch"
            # the batch span is the root of its OWN trace: requests attach
            # by span links, not parent edges
            assert batch["args"]["parent_id"] is None
            chains += 1
        assert chains >= 3                      # one dispatch per request
        admissions = [e for e in ev if e["name"] == "admission"]
        assert admissions and all(
            by_id[a["args"]["parent_id"]]["name"] == "predict"
            for a in admissions)
        # every admission span names the batch that served its request, and
        # the link exports as a flow-event pair (request lane <-> batch lane)
        batch_ids = {e["args"]["span_id"] for e in ev if e["name"] == "batch"}
        assert all(a["args"]["batch_span_id"] in batch_ids
                   for a in admissions)
        flows = [e for e in trace["traceEvents"] if e.get("cat") == "link"]
        assert flows and {e["ph"] for e in flows} == {"s", "f"}
    finally:
        server.stop()


def test_batcher_compile_accounting_once_per_bucket():
    """The first dispatch of a new (signature, bucket) is the compile; the
    steady state must add none."""
    from deeplearning4j_tpu.serving import ServingServer
    server = ServingServer(StubModel(), max_latency_ms=1.0)
    server.batcher.start()
    try:
        rng = np.random.default_rng(0)
        for rows in (3, 4):                     # both pad to bucket 4
            server.predict(rng.normal(size=(rows, 5)).astype(np.float32))
        assert server.compile_tracker.total() == 1
        for rows in (3, 4, 3):
            server.predict(rng.normal(size=(rows, 5)).astype(np.float32))
        assert server.compile_tracker.total() == 1
        server.predict(rng.normal(size=(2, 5)).astype(np.float32))
        assert server.compile_tracker.total() == 2
        assert server.compile_tracker.by_bucket() != {}
    finally:
        server.stop()


# --------------------------------------------------------------- UI scrape

def test_ui_server_metrics_endpoint_json_and_prometheus():
    from deeplearning4j_tpu.ui import UIServer
    reg = MetricsRegistry()
    reg.counter("training_iterations_total").inc(7)
    server = UIServer(port=0, registry=reg).start()
    try:
        with urllib.request.urlopen(server.url + "/metrics", timeout=30) as r:
            snap = json.loads(r.read())
        assert snap["training_iterations_total"] == 7
        with urllib.request.urlopen(
                server.url + "/metrics?format=prometheus", timeout=30) as r:
            text = r.read().decode()
        assert "training_iterations_total 7" in text
    finally:
        server.stop()


def test_ui_overview_ignores_telemetry_reports():
    """Telemetry registry flushes must not pollute the training overview."""
    from deeplearning4j_tpu.ui import UIServer, InMemoryStatsStorage
    storage = InMemoryStatsStorage()
    storage.put_update({"type": "telemetry", "session_id": "s",
                        "metrics": {}})
    storage.put_update({"type": "stats", "session_id": "s", "iteration": 1,
                        "score": 0.25})
    server = UIServer(port=0).attach(storage).start()
    try:
        with urllib.request.urlopen(server.url + "/train/overview?sid=s",
                                    timeout=30) as r:
            ov = json.loads(r.read())
        assert ov["scores"] == [0.25]
    finally:
        server.stop()


# -------------------------------------------------------------- smoke tool

def test_smoke_telemetry_tool():
    """Fast variant of tools/smoke_telemetry.py: serve requests, assert a
    non-empty prometheus scrape and a valid, nested Chrome-trace export."""
    import tools.smoke_telemetry as smoke
    out = smoke.run(n_requests=8, concurrency=4)
    assert out["requests"] == 8
    assert out["span_tree_depth"] >= 2
    assert out["span_link_flows"] > 0
    assert out["scrape_bytes"] > 0


def test_serving_dispatches_under_manual_clock(manual_clock):
    """Regression: a frozen ManualClock (the deterministic-test setup) must
    not make the batcher's coalescing window spin forever — the real-time
    condition wait bounds it."""
    from deeplearning4j_tpu.serving import ServingServer
    server = ServingServer(StubModel(), max_latency_ms=5.0)
    server.batcher.start()
    try:
        res = server.predict(np.ones((2, 3), dtype=np.float32), wait_s=30.0)
        assert res["prediction"].shape == (2, 3)
    finally:
        server.stop()


def test_enable_tracing_flips_default_tracer_in_place():
    """Regression: components capture get_tracer() at construction;
    enable_tracing() must enable that same instance, not swap in a new one."""
    from deeplearning4j_tpu.telemetry import enable_tracing, get_tracer
    captured = get_tracer()
    was_enabled = captured.enabled
    try:
        t = enable_tracing()
        assert t is captured and captured.enabled
    finally:
        captured.enabled = was_enabled


def test_batcher_failed_dispatch_span_is_exported():
    """A model error must still finish the dispatch span (tagged error) —
    the failing dispatch is what an operator looks for in /trace."""
    from deeplearning4j_tpu.serving import ServingServer

    class Broken:
        def output(self, x):
            raise ValueError("bad feature count")

    server = ServingServer(Broken(), max_latency_ms=1.0)
    server.batcher.start()
    try:
        with pytest.raises(ValueError):
            server.predict(np.ones((1, 3), dtype=np.float32), wait_s=30.0)
        names = [s.name for s in server.tracer.finished_spans()]
        assert "dispatch" in names
        d = next(s for s in server.tracer.finished_spans()
                 if s.name == "dispatch")
        assert d.attributes.get("error") == "ValueError"
        assert d.end_mono is not None
    finally:
        server.stop()


def test_broker_stop_releases_depth_gauge():
    from deeplearning4j_tpu.streaming.broker import MessageBroker
    reg = MetricsRegistry()
    broker = MessageBroker(port=0, registry=reg).start()
    broker._topic("t")
    assert reg.gauge("streaming_topic_depth").get() == {"t": 0}
    broker.stop()
    assert reg.gauge("streaming_topic_depth").get() == {}


def test_streaming_broker_registers_central_metrics():
    from deeplearning4j_tpu.streaming.broker import BrokerClient, MessageBroker
    reg = MetricsRegistry()
    broker = MessageBroker(port=0, registry=reg).start()
    try:
        client = BrokerClient(port=broker.port)
        client.publish("t1", {"v": 1})
        client.publish("t1", {"v": 2})
        assert client.poll("t1")["v"] == 1
        assert reg.counter("streaming_published_total").get(topic="t1") == 2
        assert reg.counter("streaming_polled_total").get(topic="t1") == 1
        depths = reg.gauge("streaming_topic_depth").get()
        assert depths == {"t1": 1}
        client.close()
    finally:
        broker.stop()
