"""Preemption / checkpoint-restart tests (SURVEY.md §5 must-add: TPUs are
preemptible; the driver must survive a killed process and continue the loss
curve from the last checkpoint, mid-epoch included)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd)
from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer


def _factory(seed=11):
    def make():
        conf = (NeuralNetConfiguration.builder()
                .seed(seed).updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
                .input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf)
    return make


def _data(n=80, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    return X, Y


def test_checkpoint_resume_in_process(tmp_path):
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 10 batches/epoch

    # uninterrupted reference run
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    # interrupted run: train only epoch 1 (10 iters) with freq 7 -> last
    # checkpoint at iteration 7; then build a NEW trainer from the same dir
    # (as a restarted process would) and finish
    ck = CheckpointConfig(tmp_path / "ckpt", frequency=7)
    t1 = FaultTolerantTrainer(_factory(), ck)
    assert not t1.resumed
    t1.fit(it, epochs=1)  # checkpoints at 7, 10(final)

    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed
    assert t2.state["iteration"] == 10 and t2.state["epoch"] == 1
    t2.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)


_KILLED_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {testdir!r})
    import jax
    # numerics must match the pytest parent (conftest.py): CPU + x64 enabled,
    # else replayed steps drift by ~1e-4 and the bitwise comparison fails
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from test_fault_tolerance import _factory, _data
    from deeplearning4j_tpu import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(_factory(), CheckpointConfig({ckdir!r},
                                                                frequency=5))

    class Killer:
        def iteration_done(self, model, iteration):
            if trainer.state["iteration"] >= 12:
                os._exit(17)   # hard preemption: no cleanup, no atexit
        def on_epoch_start(self, model):
            pass
        def on_epoch_end(self, model):
            pass
        def record_batch_size(self, b):
            pass

    trainer.model.set_listeners(Killer())
    trainer.fit(it, epochs=2)
    os._exit(0)  # unreachable if the kill fired
""")


def test_preemption_kill_and_resume_matches_uninterrupted(tmp_path):
    """Kill the training process mid-epoch (SIGKILL-style os._exit), resume in
    a fresh trainer, and require the final params to MATCH an uninterrupted
    run bit-for-bit in replayed batch order (checkpointed rng + iterator
    position make the resume deterministic)."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    ckdir = str(tmp_path / "ckpt")
    script = _KILLED_SCRIPT.format(repo=os.getcwd(),
                                   testdir=os.path.dirname(__file__),
                                   ckdir=ckdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 17, proc.stderr.decode()[-2000:]

    t = FaultTolerantTrainer(_factory(), CheckpointConfig(ckdir, frequency=5))
    assert t.resumed
    # the process died at iteration 12; the newest surviving checkpoint is 10
    assert t.state["iteration"] == 10
    t.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t.model.get_flat_params(), rtol=1e-6, atol=1e-7)


def test_checkpoint_gc_keeps_last(tmp_path):
    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 5 batches/epoch
    ck = CheckpointConfig(tmp_path / "ck", frequency=2, keep_last=2)
    t = FaultTolerantTrainer(_factory(), ck)
    t.fit(it, epochs=2)  # iters 1..10, ckpts at 2,4,6,8,10 + final
    names = sorted(os.listdir(ck.directory))
    assert len([n for n in names if n.startswith("ckpt-")]) <= 2


def test_checkpoint_resume_sharded_format(tmp_path):
    """FaultTolerantTrainer with the orbax sharded tensor-store format
    (CheckpointConfig(format='sharded')) resumes identically to zip."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "sc", frequency=7, format="sharded")
    t1 = FaultTolerantTrainer(_factory(), ck)
    t1.fit(it, epochs=1)
    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed and t2.state["iteration"] == 10
    np.testing.assert_allclose(t1.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=0, atol=0)
    t2.fit(it, epochs=2)

    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "rf",
                                                            frequency=0))
    ref.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)
