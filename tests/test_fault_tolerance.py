"""Preemption / checkpoint-restart tests (SURVEY.md §5 must-add: TPUs are
preemptible; the driver must survive a killed process and continue the loss
curve from the last checkpoint, mid-epoch included)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd)
from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer


def _factory(seed=11):
    def make():
        conf = (NeuralNetConfiguration.builder()
                .seed(seed).updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
                .input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf)
    return make


def _data(n=80, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    return X, Y


def test_checkpoint_resume_in_process(tmp_path):
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 10 batches/epoch

    # uninterrupted reference run
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    # interrupted run: train only epoch 1 (10 iters) with freq 7 -> last
    # checkpoint at iteration 7; then build a NEW trainer from the same dir
    # (as a restarted process would) and finish
    ck = CheckpointConfig(tmp_path / "ckpt", frequency=7)
    t1 = FaultTolerantTrainer(_factory(), ck)
    assert not t1.resumed
    t1.fit(it, epochs=1)  # checkpoints at 7, 10(final)

    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed
    assert t2.state["iteration"] == 10 and t2.state["epoch"] == 1
    t2.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)


_KILLED_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {testdir!r})
    import jax
    # numerics must match the pytest parent (conftest.py): CPU + x64 enabled,
    # else replayed steps drift by ~1e-4 and the bitwise comparison fails
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from test_fault_tolerance import _factory, _data
    from deeplearning4j_tpu import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(_factory(), CheckpointConfig({ckdir!r},
                                                                frequency=5))

    class Killer:
        def iteration_done(self, model, iteration):
            if trainer.state["iteration"] >= 12:
                # the async writer may still be publishing ckpt-10: join it
                # (the preemption-grace flush a real SIGTERM handler does)
                # so the newest surviving checkpoint is deterministically 10
                trainer.drain_checkpoints(raise_errors=False)
                os._exit(17)   # hard preemption: no cleanup, no atexit
        def on_epoch_start(self, model):
            pass
        def on_epoch_end(self, model):
            pass
        def record_batch_size(self, b):
            pass

    trainer.model.set_listeners(Killer())
    trainer.fit(it, epochs=2)
    os._exit(0)  # unreachable if the kill fired
""")


def test_preemption_kill_and_resume_matches_uninterrupted(tmp_path):
    """Kill the training process mid-epoch (SIGKILL-style os._exit), resume in
    a fresh trainer, and require the final params to MATCH an uninterrupted
    run bit-for-bit in replayed batch order (checkpointed rng + iterator
    position make the resume deterministic)."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    ckdir = str(tmp_path / "ckpt")
    script = _KILLED_SCRIPT.format(repo=os.getcwd(),
                                   testdir=os.path.dirname(__file__),
                                   ckdir=ckdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 17, proc.stderr.decode()[-2000:]

    t = FaultTolerantTrainer(_factory(), CheckpointConfig(ckdir, frequency=5))
    assert t.resumed
    # the process died at iteration 12; the newest surviving checkpoint is 10
    assert t.state["iteration"] == 10
    t.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t.model.get_flat_params(), rtol=1e-6, atol=1e-7)


def test_checkpoint_gc_keeps_last(tmp_path):
    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 5 batches/epoch
    ck = CheckpointConfig(tmp_path / "ck", frequency=2, keep_last=2)
    t = FaultTolerantTrainer(_factory(), ck)
    t.fit(it, epochs=2)  # iters 1..10, ckpts at 2,4,6,8,10 + final
    names = sorted(os.listdir(ck.directory))
    assert len([n for n in names if n.startswith("ckpt-")]) <= 2


def test_checkpoint_resume_sharded_format(tmp_path):
    """FaultTolerantTrainer with the orbax sharded tensor-store format
    (CheckpointConfig(format='sharded')) resumes identically to zip."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "sc", frequency=7, format="sharded")
    t1 = FaultTolerantTrainer(_factory(), ck)
    t1.fit(it, epochs=1)
    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed and t2.state["iteration"] == 10
    np.testing.assert_allclose(t1.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=0, atol=0)
    t2.fit(it, epochs=2)

    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "rf",
                                                            frequency=0))
    ref.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)


def test_trainer_health_probe_survives_restore(tmp_path):
    """Elastic-fleet satellite regression: the trainer registers a liveness
    probe into the health monitor, and the RESTORE path re-registers it
    with primed heartbeat state — a resumed run is immediately visible on
    /healthz (and so /fleet/healthz), at its restored iteration, instead
    of silently losing its membership entry."""
    from deeplearning4j_tpu.telemetry.health import HealthMonitor

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=7)

    m1 = HealthMonitor()
    t1 = FaultTolerantTrainer(_factory(), ck, monitor=m1)
    assert t1.health_key in m1.components()
    comp = m1.check()["components"][t1.health_key]
    assert comp["status"] == "healthy" and comp["iteration"] == 0
    assert comp["resumed"] is False and comp["last_step_age_s"] is None
    t1.fit(it, epochs=1)
    comp = m1.check()["components"][t1.health_key]
    assert comp["iteration"] == 10 and comp["last_step_age_s"] is not None

    # a restarted process: fresh monitor, fresh trainer, same directory —
    # the probe must be re-registered and report the restored state as a
    # LIVE (heartbeat-primed) member
    m2 = HealthMonitor()
    t2 = FaultTolerantTrainer(_factory(), ck, monitor=m2)
    assert t2.resumed
    comp = m2.check()["components"][t2.health_key]
    assert comp["status"] == "healthy"
    assert comp["iteration"] == 10 and comp["resumed"] is True
    assert comp["last_step_age_s"] is not None

    # probe withdrawal for drivers that shut the run down
    t2.unregister_probe()
    assert t2.health_key is None and m2.components() == []
    # monitor=False opts out entirely
    t3 = FaultTolerantTrainer(_factory(), ck, monitor=False)
    assert t3.monitor is None and t3.health_key is None


def test_async_and_sync_checkpoints_bit_identical(tmp_path):
    """The async snapshot-then-write path must serialize EXACTLY what the
    synchronous path does: same training run, async_write on vs off, the
    model zip and training state BYTE-identical on disk (write_model emits
    deterministic zip entries — fixed DOS timestamps — precisely so this
    holds), manifests recording identical digests."""
    X, Y = _data()
    dirs = {}
    for mode, async_write in (("async", True), ("sync", False)):
        it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
        ck = CheckpointConfig(tmp_path / mode, frequency=7,
                              async_write=async_write)
        assert ck.async_write is async_write
        t = FaultTolerantTrainer(_factory(), ck)
        t.fit(it, epochs=1)
        dirs[mode] = ck.directory
    a = os.path.join(dirs["async"], "ckpt-000000010")
    s = os.path.join(dirs["sync"], "ckpt-000000010")
    for name in ("model.zip", FaultTolerantTrainer.STATE_FILE):
        with open(os.path.join(a, name), "rb") as f1, \
                open(os.path.join(s, name), "rb") as f2:
            assert f1.read() == f2.read(), name
    from deeplearning4j_tpu.util import fs
    ma, ms = fs.read_manifest(a), fs.read_manifest(s)
    assert ma["files"] == ms["files"]
    assert ma["step"] == ms["step"] == 10


def test_keep_every_anchor_checkpoints_survive_gc(tmp_path):
    """CheckpointConfig(keep_every=K): iteration-multiple-of-K checkpoints
    are anchors — kept outside the keep_last window."""
    X, Y = _data()                                   # 10 batches/epoch
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=2, keep_last=1,
                          keep_every=4)
    t = FaultTolerantTrainer(_factory(), ck)
    t.fit(it, epochs=1)  # ckpts at 2,4,6,8,10; anchors 4,8; last 10
    names = sorted(n for n in os.listdir(ck.directory)
                   if n.startswith("ckpt-"))
    assert names == ["ckpt-000000004", "ckpt-000000008", "ckpt-000000010"]
    for n in names:
        from deeplearning4j_tpu.util import fs
        ok, errors = fs.verify_manifest(os.path.join(ck.directory, n))
        assert ok, (n, errors)


def test_gc_never_deletes_last_verified_good(tmp_path):
    """Even when the last verified-good checkpoint falls outside keep_last,
    _gc retains it — if everything newer later turns out corrupt, it is
    the restore of record."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    import shutil

    ck = CheckpointConfig(tmp_path / "ck", frequency=5, keep_last=1)
    t = FaultTolerantTrainer(_factory(), ck)
    t.fit(it, epochs=1)                       # keep_last=1 -> only ckpt-10
    assert [n for n in sorted(os.listdir(ck.directory))
            if n.startswith("ckpt-")] == ["ckpt-000000010"]
    # fabricate newer checkpoints (the restore-fallback window: newer dirs
    # exist on disk but the VERIFIED one is older), then GC with window 1
    for it_n in (20, 25):
        shutil.copytree(os.path.join(ck.directory, "ckpt-000000010"),
                        os.path.join(ck.directory, f"ckpt-{it_n:09d}"))
    t._last_good = "ckpt-000000010"
    t._gc()
    names = sorted(n for n in os.listdir(ck.directory)
                   if n.startswith("ckpt-"))
    assert names == ["ckpt-000000010", "ckpt-000000025"]


def test_restore_falls_back_past_manually_corrupted_chain(tmp_path):
    """Both newest checkpoints corrupted on disk (no chaos plan — raw byte
    damage): restore quarantines BOTH, restores the third-newest, and the
    fallback counter/probe reflect it."""
    from deeplearning4j_tpu.telemetry.health import HealthMonitor
    from deeplearning4j_tpu.telemetry.registry import get_registry

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=3, keep_last=4)
    t1 = FaultTolerantTrainer(_factory(), ck)
    t1.fit(it, epochs=1)                           # ckpts 3, 6, 9, 10
    for n in ("ckpt-000000009", "ckpt-000000010"):
        p = os.path.join(ck.directory, n, "model.zip")
        with open(p, "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0xFF]))
    v0 = get_registry().counter("ckpt_verify_failures_total").get()
    mon = HealthMonitor()
    t2 = FaultTolerantTrainer(_factory(), ck, monitor=mon)
    assert t2.resumed and t2.state["iteration"] == 6
    assert get_registry().counter("ckpt_verify_failures_total").get() \
        == v0 + 2
    quarantined = sorted(n for n in os.listdir(ck.directory)
                         if n.startswith("corrupt-"))
    assert quarantined == ["corrupt-ckpt-000000009",
                           "corrupt-ckpt-000000010"]
    comp = mon.check()["components"][t2.health_key]
    assert comp["status"] == "degraded"
    assert comp["checkpoint_debt"]["quarantined"] == 2
    t2.unregister_probe()


def test_legacy_checkpoint_without_manifest_is_quarantined(tmp_path):
    """A checkpoint with no MANIFEST.json is by definition incomplete:
    quarantined on restore, with the fresh-model path taken when nothing
    verifies — and ckpt_doctor's `manifest` command can re-bless it."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=0)
    t1 = FaultTolerantTrainer(_factory(), ck)
    t1.fit(it, epochs=1)                           # final ckpt-10 only
    man = os.path.join(ck.directory, "ckpt-000000010", "MANIFEST.json")
    os.unlink(man)
    t2 = FaultTolerantTrainer(_factory(), ck)
    assert not t2.resumed and t2.state["iteration"] == 0
    corrupt = [n for n in os.listdir(ck.directory)
               if n.startswith("corrupt-")]
    assert corrupt == ["corrupt-ckpt-000000010"]
    # operator re-blesses the quarantined dir and moves it back
    from tools import ckpt_doctor
    src = os.path.join(ck.directory, corrupt[0])
    assert ckpt_doctor.cmd_manifest(src) == 0
    os.rename(src, os.path.join(ck.directory, "ckpt-000000010"))
    t3 = FaultTolerantTrainer(_factory(), ck)
    assert t3.resumed and t3.state["iteration"] == 10


def test_manifest_shape_and_doctor_cli(tmp_path, capsys):
    """MANIFEST.json carries per-file sha256+bytes, step, wall time,
    topology, format; ckpt_doctor verify/list/quarantine drive the same
    primitives from the CLI."""
    from deeplearning4j_tpu.util import fs
    from tools import ckpt_doctor

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=7)
    FaultTolerantTrainer(_factory(), ck).fit(it, epochs=1)
    man = fs.read_manifest(os.path.join(ck.directory, "ckpt-000000010"))
    assert man["step"] == 10 and man["format"] == "zip"
    assert man["version"] == 1 and man["wall_time_s"] > 0
    assert set(man["files"]) == {"model.zip", "train_state.json"}
    for entry in man["files"].values():
        assert len(entry["sha256"]) == 64 and entry["bytes"] > 0
    assert man["topology"]["process_count"] >= 1
    assert man["topology"]["device_count"] >= 1

    assert ckpt_doctor.main(["verify", ck.directory]) == 0
    assert ckpt_doctor.main(["list", ck.directory]) == 0
    # flip one byte -> verify fails with a sha256 error, exit 1
    p = os.path.join(ck.directory, "ckpt-000000010", "model.zip")
    with open(p, "r+b") as f:
        f.seek(50)
        b = f.read(1)
        f.seek(50)
        f.write(bytes([b[0] ^ 0x01]))
    assert ckpt_doctor.main(["verify", ck.directory]) == 1
    out = capsys.readouterr().out
    assert "sha256 mismatch" in out
    assert ckpt_doctor.main(
        ["quarantine", ck.directory, "ckpt-000000010"]) == 0
    assert os.path.isdir(
        os.path.join(ck.directory, "corrupt-ckpt-000000010"))
    assert ckpt_doctor.main(["verify", ck.directory]) == 0  # 12 remains ok


def test_smoke_ckpt_tool(tmp_path):
    """The full durable-checkpoint arc (tools/smoke_ckpt.py): train with
    async checkpoints under a seeded disk-fault plan (slow_disk advancing a
    ManualClock — zero real sleeps), torn_write AND bitflip on the newest
    checkpoint each followed by restore-with-fallback + final-param parity
    vs an uninterrupted run, and an ENOSPC mid-checkpoint that leaves
    training running with the prior published checkpoint intact."""
    import tools.smoke_ckpt as smoke
    out = smoke.run(str(tmp_path))
    assert out["tear_parity"] and out["flip_parity"]
    assert out["tear_fallbacks"] == 1 and out["flip_fallbacks"] == 1
    assert out["tear_verify_failures"] == 1
    assert out["flip_verify_failures"] == 1
    assert out["enospc_write_failures"] == 1
    assert out["enospc_survivors"] == ["ckpt-000000005", "ckpt-000000012"]
    assert out["ckpt_write_ms_count"] > 0
    assert out["tear_clock_advance_s"] >= 0.15  # simulated, not slept


def test_trainer_probe_visible_through_fleet_healthz(tmp_path):
    """The probe lands on the PROCESS monitor by default, which UIServer
    /healthz aggregates and FleetCollector scrapes — a training run shows
    up on /fleet/healthz with its iteration/heartbeat detail."""
    from deeplearning4j_tpu.telemetry.fleet import FleetServer
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.http import get_json

    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(_factory(),
                                   CheckpointConfig(tmp_path / "ck",
                                                    frequency=0))
    try:
        trainer.fit(it, epochs=1)
        ui = UIServer(port=0).start()
        fleet = FleetServer([ui.url], names=["trainer-host"],
                            interval_s=0.0).start()
        try:
            report = get_json(fleet.url + "/fleet/healthz", timeout=30)
            host = report["components"]["trainer-host"]
            assert host["status"] == "healthy"
            comps = host["components"]
            assert trainer.health_key in comps
            assert comps[trainer.health_key]["iteration"] == 5
        finally:
            fleet.stop()
            ui.stop()
    finally:
        trainer.unregister_probe()
