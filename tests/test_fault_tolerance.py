"""Preemption / checkpoint-restart tests (SURVEY.md §5 must-add: TPUs are
preemptible; the driver must survive a killed process and continue the loss
curve from the last checkpoint, mid-epoch included)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd)
from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer


def _factory(seed=11):
    def make():
        conf = (NeuralNetConfiguration.builder()
                .seed(seed).updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
                .input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf)
    return make


def _data(n=80, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    return X, Y


def test_checkpoint_resume_in_process(tmp_path):
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 10 batches/epoch

    # uninterrupted reference run
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    # interrupted run: train only epoch 1 (10 iters) with freq 7 -> last
    # checkpoint at iteration 7; then build a NEW trainer from the same dir
    # (as a restarted process would) and finish
    ck = CheckpointConfig(tmp_path / "ckpt", frequency=7)
    t1 = FaultTolerantTrainer(_factory(), ck)
    assert not t1.resumed
    t1.fit(it, epochs=1)  # checkpoints at 7, 10(final)

    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed
    assert t2.state["iteration"] == 10 and t2.state["epoch"] == 1
    t2.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)


_KILLED_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {testdir!r})
    import jax
    # numerics must match the pytest parent (conftest.py): CPU + x64 enabled,
    # else replayed steps drift by ~1e-4 and the bitwise comparison fails
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from test_fault_tolerance import _factory, _data
    from deeplearning4j_tpu import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(_factory(), CheckpointConfig({ckdir!r},
                                                                frequency=5))

    class Killer:
        def iteration_done(self, model, iteration):
            if trainer.state["iteration"] >= 12:
                os._exit(17)   # hard preemption: no cleanup, no atexit
        def on_epoch_start(self, model):
            pass
        def on_epoch_end(self, model):
            pass
        def record_batch_size(self, b):
            pass

    trainer.model.set_listeners(Killer())
    trainer.fit(it, epochs=2)
    os._exit(0)  # unreachable if the kill fired
""")


def test_preemption_kill_and_resume_matches_uninterrupted(tmp_path):
    """Kill the training process mid-epoch (SIGKILL-style os._exit), resume in
    a fresh trainer, and require the final params to MATCH an uninterrupted
    run bit-for-bit in replayed batch order (checkpointed rng + iterator
    position make the resume deterministic)."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                            frequency=0))
    ref.fit(it, epochs=2)

    ckdir = str(tmp_path / "ckpt")
    script = _KILLED_SCRIPT.format(repo=os.getcwd(),
                                   testdir=os.path.dirname(__file__),
                                   ckdir=ckdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 17, proc.stderr.decode()[-2000:]

    t = FaultTolerantTrainer(_factory(), CheckpointConfig(ckdir, frequency=5))
    assert t.resumed
    # the process died at iteration 12; the newest surviving checkpoint is 10
    assert t.state["iteration"] == 10
    t.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t.model.get_flat_params(), rtol=1e-6, atol=1e-7)


def test_checkpoint_gc_keeps_last(tmp_path):
    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)  # 5 batches/epoch
    ck = CheckpointConfig(tmp_path / "ck", frequency=2, keep_last=2)
    t = FaultTolerantTrainer(_factory(), ck)
    t.fit(it, epochs=2)  # iters 1..10, ckpts at 2,4,6,8,10 + final
    names = sorted(os.listdir(ck.directory))
    assert len([n for n in names if n.startswith("ckpt-")]) <= 2


def test_checkpoint_resume_sharded_format(tmp_path):
    """FaultTolerantTrainer with the orbax sharded tensor-store format
    (CheckpointConfig(format='sharded')) resumes identically to zip."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "sc", frequency=7, format="sharded")
    t1 = FaultTolerantTrainer(_factory(), ck)
    t1.fit(it, epochs=1)
    t2 = FaultTolerantTrainer(_factory(), ck)
    assert t2.resumed and t2.state["iteration"] == 10
    np.testing.assert_allclose(t1.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=0, atol=0)
    t2.fit(it, epochs=2)

    ref = FaultTolerantTrainer(_factory(), CheckpointConfig(tmp_path / "rf",
                                                            frequency=0))
    ref.fit(it, epochs=2)
    np.testing.assert_allclose(ref.model.get_flat_params(),
                               t2.model.get_flat_params(), rtol=1e-6, atol=1e-7)


def test_trainer_health_probe_survives_restore(tmp_path):
    """Elastic-fleet satellite regression: the trainer registers a liveness
    probe into the health monitor, and the RESTORE path re-registers it
    with primed heartbeat state — a resumed run is immediately visible on
    /healthz (and so /fleet/healthz), at its restored iteration, instead
    of silently losing its membership entry."""
    from deeplearning4j_tpu.telemetry.health import HealthMonitor

    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=7)

    m1 = HealthMonitor()
    t1 = FaultTolerantTrainer(_factory(), ck, monitor=m1)
    assert t1.health_key in m1.components()
    comp = m1.check()["components"][t1.health_key]
    assert comp["status"] == "healthy" and comp["iteration"] == 0
    assert comp["resumed"] is False and comp["last_step_age_s"] is None
    t1.fit(it, epochs=1)
    comp = m1.check()["components"][t1.health_key]
    assert comp["iteration"] == 10 and comp["last_step_age_s"] is not None

    # a restarted process: fresh monitor, fresh trainer, same directory —
    # the probe must be re-registered and report the restored state as a
    # LIVE (heartbeat-primed) member
    m2 = HealthMonitor()
    t2 = FaultTolerantTrainer(_factory(), ck, monitor=m2)
    assert t2.resumed
    comp = m2.check()["components"][t2.health_key]
    assert comp["status"] == "healthy"
    assert comp["iteration"] == 10 and comp["resumed"] is True
    assert comp["last_step_age_s"] is not None

    # probe withdrawal for drivers that shut the run down
    t2.unregister_probe()
    assert t2.health_key is None and m2.components() == []
    # monitor=False opts out entirely
    t3 = FaultTolerantTrainer(_factory(), ck, monitor=False)
    assert t3.monitor is None and t3.health_key is None


def test_trainer_probe_visible_through_fleet_healthz(tmp_path):
    """The probe lands on the PROCESS monitor by default, which UIServer
    /healthz aggregates and FleetCollector scrapes — a training run shows
    up on /fleet/healthz with its iteration/heartbeat detail."""
    from deeplearning4j_tpu.telemetry.fleet import FleetServer
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.http import get_json

    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = FaultTolerantTrainer(_factory(),
                                   CheckpointConfig(tmp_path / "ck",
                                                    frequency=0))
    try:
        trainer.fit(it, epochs=1)
        ui = UIServer(port=0).start()
        fleet = FleetServer([ui.url], names=["trainer-host"],
                            interval_s=0.0).start()
        try:
            report = get_json(fleet.url + "/fleet/healthz", timeout=30)
            host = report["components"]["trainer-host"]
            assert host["status"] == "healthy"
            comps = host["components"]
            assert trainer.health_key in comps
            assert comps[trainer.health_key]["iteration"] == 5
        finally:
            fleet.stop()
            ui.stop()
    finally:
        trainer.unregister_probe()
