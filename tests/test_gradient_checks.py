"""Gradient checks — the correctness backbone, mirroring the reference's
deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/ suites
(GradientCheckTests, CNNGradientCheckTest, BNGradientCheckTest,
LRNGradientCheckTests, GlobalPoolingGradientCheckTests, VaeGradientCheckTests,
LossFunctionGradientCheck, GradientCheckTestsMasking).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, RnnOutputLayer, ConvolutionLayer,
                                SubsamplingLayer, BatchNormalization, GravesLSTM,
                                LSTM, GravesBidirectionalLSTM, EmbeddingLayer,
                                GlobalPoolingLayer, ActivationLayer,
                                LocalResponseNormalization, ZeroPaddingLayer,
                                AutoEncoder, VariationalAutoencoder,
                                MultiLayerNetwork, Sgd, NoOp, WeightInit)
from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients


def _onehot(idx, n):
    return np.eye(n)[idx]


def _rand_cls(rng, b, nin, nout):
    x = rng.normal(size=(b, nin))
    y = _onehot(rng.integers(0, nout, b), nout)
    return x, y


def _build(layers, input_type, **kw):
    b = (NeuralNetConfiguration.builder()
         .seed(12345)
         .updater(NoOp())
         .dtype("float64")
         .weight_init(kw.get("weight_init", WeightInit.XAVIER)))
    if "l1" in kw:
        b = b.l1(kw["l1"])
    if "l2" in kw:
        b = b.l2(kw["l2"])
    lb = b.list()
    for l in layers:
        lb.layer(l)
    lb.set_input_type(input_type)
    return MultiLayerNetwork(lb.build()).init()


@pytest.mark.parametrize("act,loss,out_act", [
    ("relu", "MCXENT", "softmax"),
    ("tanh", "MSE", "identity"),
    ("sigmoid", "XENT", "sigmoid"),
    ("elu", "MCXENT", "softmax"),
    ("softplus", "L2", "tanh"),
])
def test_dense_gradients(act, loss, out_act):
    rng = np.random.default_rng(0)
    x, y = _rand_cls(rng, 8, 5, 3)
    if loss == "XENT":
        y = (rng.random((8, 3)) > 0.5).astype(float)
    net = _build([DenseLayer(n_out=6, activation=act),
                  OutputLayer(n_out=3, activation=out_act, loss=loss)],
                 InputType.feed_forward(5))
    assert check_gradients(net, x, y, print_results=True)


def test_dense_l1_l2_gradients():
    rng = np.random.default_rng(1)
    x, y = _rand_cls(rng, 8, 5, 3)
    net = _build([DenseLayer(n_out=6, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="MCXENT")],
                 InputType.feed_forward(5), l1=0.01, l2=0.02)
    assert check_gradients(net, x, y, print_results=True)


def test_cnn_gradients():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 8, 8, 2))
    y = _onehot(rng.integers(0, 3, 4), 3)
    net = _build([ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1), n_out=4,
                                   activation="tanh"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="max"),
                  OutputLayer(n_out=3, activation="softmax", loss="MCXENT")],
                 InputType.convolutional(8, 8, 2))
    assert check_gradients(net, x, y, print_results=True)


def test_cnn_avg_pool_zeropad_gradients():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(3, 6, 6, 1))
    y = _onehot(rng.integers(0, 2, 3), 2)
    net = _build([ZeroPaddingLayer(pad_top=1, pad_bottom=1, pad_left=1, pad_right=1),
                  ConvolutionLayer(kernel_size=(3, 3), n_out=3, activation="sigmoid"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="avg"),
                  OutputLayer(n_out=2, activation="softmax", loss="MCXENT")],
                 InputType.convolutional(6, 6, 1))
    assert check_gradients(net, x, y, print_results=True)


def test_batchnorm_gradients():
    rng = np.random.default_rng(4)
    x, y = _rand_cls(rng, 8, 5, 3)
    net = _build([DenseLayer(n_out=6, activation="identity"),
                  BatchNormalization(),
                  ActivationLayer(activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="MCXENT")],
                 InputType.feed_forward(5))
    # BN uses batch statistics in train mode; check against train=False forward
    # with running stats is inconsistent, so we check the train-mode loss:
    # achieved by computing grads of the train-mode loss directly.
    import jax, jax.numpy as jnp
    x64 = jnp.asarray(x, jnp.float64)
    y64 = jnp.asarray(y, jnp.float64)
    net.params = jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float64), net.params)
    net.states = jax.tree_util.tree_map(lambda s: jnp.asarray(s, jnp.float64), net.states)

    def loss_fn(p):
        s, _ = net._loss(p, net.states, x64, y64, train=True, rng=None)
        return s
    grads = jax.grad(loss_fn)(net.params)
    eps = 1e-6
    import numpy as onp
    for lk in net.params:
        for pn, arr in net.params[lk].items():
            flat = onp.asarray(arr).ravel().copy()
            gf = onp.asarray(grads[lk][pn]).ravel()
            for i in range(min(flat.size, 20)):
                orig = flat[i]
                for sgn, store in ((1, "p"), (-1, "m")):
                    flat[i] = orig + sgn * eps
                    newp = {k: dict(v) for k, v in net.params.items()}
                    newp[lk][pn] = jnp.asarray(flat.reshape(arr.shape))
                    val = float(loss_fn(newp))
                    if sgn == 1:
                        sp = val
                    else:
                        sm = val
                flat[i] = orig
                numeric = (sp - sm) / (2 * eps)
                denom = abs(numeric) + abs(gf[i])
                rel = abs(numeric - gf[i]) / denom if denom else 0.0
                assert rel < 1e-3 or abs(numeric - gf[i]) < 1e-8, \
                    f"{lk}/{pn}[{i}] rel={rel}"


def test_lrn_gradients():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(3, 5, 5, 8))
    y = _onehot(rng.integers(0, 2, 3), 2)
    net = _build([ConvolutionLayer(kernel_size=(3, 3), n_out=8, activation="tanh"),
                  LocalResponseNormalization(),
                  OutputLayer(n_out=2, activation="softmax", loss="MCXENT")],
                 InputType.convolutional(5, 5, 8))
    assert check_gradients(net, x, y, print_results=True)


@pytest.mark.parametrize("layer_cls", [GravesLSTM, LSTM, GravesBidirectionalLSTM])
def test_lstm_gradients(layer_cls):
    rng = np.random.default_rng(6)
    b, t, nin, nout = 3, 4, 3, 2
    x = rng.normal(size=(b, t, nin))
    y = _onehot(rng.integers(0, nout, (b, t)).ravel(), nout).reshape(b, t, nout)
    net = _build([layer_cls(n_out=4, activation="tanh"),
                  RnnOutputLayer(n_out=nout, activation="softmax", loss="MCXENT")],
                 InputType.recurrent(nin))
    assert check_gradients(net, x, y, print_results=True)


def test_lstm_masking_gradients():
    rng = np.random.default_rng(7)
    b, t, nin, nout = 3, 5, 3, 2
    x = rng.normal(size=(b, t, nin))
    y = _onehot(rng.integers(0, nout, (b, t)).ravel(), nout).reshape(b, t, nout)
    mask = np.ones((b, t))
    mask[0, 3:] = 0
    mask[1, 2:] = 0
    import jax.numpy as jnp
    net = _build([GravesLSTM(n_out=4, activation="tanh"),
                  RnnOutputLayer(n_out=nout, activation="softmax", loss="MCXENT")],
                 InputType.recurrent(nin))
    assert check_gradients(net, x, y, mask=jnp.asarray(mask, jnp.float64),
                           label_mask=jnp.asarray(mask, jnp.float64),
                           print_results=True)


def test_global_pooling_gradients():
    rng = np.random.default_rng(8)
    b, t, nin, nout = 3, 5, 4, 2
    x = rng.normal(size=(b, t, nin))
    y = _onehot(rng.integers(0, nout, b), nout)
    for pt in ("max", "avg", "sum"):
        net = _build([GravesLSTM(n_out=4, activation="tanh"),
                      GlobalPoolingLayer(pooling_type=pt),
                      OutputLayer(n_out=nout, activation="softmax", loss="MCXENT")],
                     InputType.recurrent(nin))
        assert check_gradients(net, x, y, print_results=True), pt


def test_embedding_gradients():
    rng = np.random.default_rng(9)
    b, vocab, nout = 6, 10, 3
    x = rng.integers(0, vocab, (b, 1)).astype(np.float64)
    y = _onehot(rng.integers(0, nout, b), nout)
    net = _build([EmbeddingLayer(n_in=vocab, n_out=5, activation="identity"),
                  DenseLayer(n_out=4, activation="tanh"),
                  OutputLayer(n_out=nout, activation="softmax", loss="MCXENT")],
                 InputType.feed_forward(1))
    assert check_gradients(net, x, y, print_results=True)


def test_self_attention_gradients():
    """Gradient check for the multi-head self-attention layer (new
    capability; validates the blockwise/reference attention backward)."""
    from deeplearning4j_tpu import SelfAttentionLayer
    rng = np.random.default_rng(13)
    b, t, nin, nout = 2, 5, 3, 2
    x = rng.normal(size=(b, t, nin))
    y = _onehot(rng.integers(0, nout, (b, t)).ravel(), nout).reshape(b, t, nout)
    for causal in (False, True):
        net = _build([SelfAttentionLayer(n_out=4, n_heads=2,
                                         activation="identity", causal=causal),
                      RnnOutputLayer(n_out=nout, activation="softmax",
                                     loss="MCXENT")],
                     InputType.recurrent(nin))
        assert check_gradients(net, x, y, print_results=True), f"causal={causal}"


def test_self_attention_masked_gradients():
    from deeplearning4j_tpu import SelfAttentionLayer
    import jax.numpy as jnp
    rng = np.random.default_rng(14)
    b, t, nin, nout = 2, 5, 3, 2
    x = rng.normal(size=(b, t, nin))
    y = _onehot(rng.integers(0, nout, (b, t)).ravel(), nout).reshape(b, t, nout)
    mask = np.ones((b, t))
    mask[0, 3:] = 0
    net = _build([SelfAttentionLayer(n_out=4, n_heads=2, activation="identity"),
                  RnnOutputLayer(n_out=nout, activation="softmax", loss="MCXENT")],
                 InputType.recurrent(nin))
    assert check_gradients(net, x, y, mask=jnp.asarray(mask, jnp.float64),
                           label_mask=jnp.asarray(mask, jnp.float64),
                           print_results=True)
