"""Observability stack tests — mirroring the reference's ui test suites
(TestStatsListener, TestStatsStorage, ui server tests)."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd, DataSet)
from deeplearning4j_tpu.ui import (StatsListener, InMemoryStatsStorage,
                                   FileStatsStorage, RemoteUIStatsStorageRouter,
                                   CollectionStatsStorageRouter, UIServer,
                                   components)


def _net_and_data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(32, 4))
    y = np.eye(2)[(x.sum(1) > 0).astype(int)]
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init(), DataSet(x, y)


def test_stats_listener_collects_reports():
    net, ds = _net_and_data()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1, session_id="s1"))
    for _ in range(5):
        net.fit_batch(ds)
    assert storage.list_session_ids() == ["s1"]
    init = storage.get_static_info("s1")
    assert init["n_params"] == net.num_params()
    ups = storage.get_all_updates("s1")
    assert len(ups) == 5
    last = ups[-1]
    assert np.isfinite(last["score"])
    # param stats present with histograms
    key = next(iter(last["param_stats"]))
    st = last["param_stats"][key]
    assert "mean_magnitude" in st and len(st["histogram"]) == 20
    # gradient stats captured from the train step
    assert last["gradient_stats"], "expected gradient stats"
    gkey = next(iter(last["gradient_stats"]))
    assert last["gradient_stats"][gkey]["mean_magnitude"] >= 0


def test_file_stats_storage_roundtrip(tmp_path):
    net, ds = _net_and_data(1)
    p = tmp_path / "stats.jsonl"
    storage = FileStatsStorage(p)
    net.set_listeners(StatsListener(storage, session_id="s2"))
    for _ in range(3):
        net.fit_batch(ds)
    storage.close()
    # reload from disk
    storage2 = FileStatsStorage(p)
    assert storage2.list_session_ids() == ["s2"]
    assert len(storage2.get_all_updates("s2")) == 3
    assert storage2.get_static_info("s2")["model_class"] == "MultiLayerNetwork"


def test_ui_server_endpoints_and_remote_router():
    server = UIServer(port=0).attach(InMemoryStatsStorage()).start()
    try:
        # remote router -> POST /remoteReceive -> storage
        router = RemoteUIStatsStorageRouter(server.url)
        net, ds = _net_and_data(2)
        net.set_listeners(StatsListener(router, session_id="remote1"))
        for _ in range(4):
            net.fit_batch(ds)
        with urllib.request.urlopen(server.url + "/train/sessions") as r:
            sessions = json.loads(r.read())
        assert "remote1" in sessions
        with urllib.request.urlopen(server.url + "/train/overview?sid=remote1") as r:
            ov = json.loads(r.read())
        assert len(ov["scores"]) == 4
        assert ov["iterations"] == [1, 2, 3, 4]
        with urllib.request.urlopen(server.url + "/train/model?sid=remote1") as r:
            model = json.loads(r.read())
        assert model["static"]["n_params"] == net.num_params()
        with urllib.request.urlopen(server.url + "/") as r:
            html = r.read()
        assert b"Training overview" in html
    finally:
        server.stop()


def test_collection_router():
    net, ds = _net_and_data(3)
    router = CollectionStatsStorageRouter()
    net.set_listeners(StatsListener(router, frequency=2, session_id="c1"))
    for _ in range(4):
        net.fit_batch(ds)
    assert len(router.static_info) == 1
    assert len(router.updates) == 2  # frequency=2


def test_components_serde():
    chart = (components.ChartLine(title="score")
             .add_series("train", [0, 1, 2], [1.0, 0.5, 0.2]))
    table = components.ComponentTable(header=["k", "v"],
                                      content=[["lr", "0.1"]], title="config")
    div = components.ComponentDiv(chart, table,
                                  components.ComponentText("hello"))
    d = div.to_dict()
    rebuilt = components.component_from_dict(json.loads(json.dumps(d)))
    assert rebuilt.to_dict() == d
    hist = components.ChartHistogram(title="h").add_bin(0, 1, 5).add_bin(1, 2, 3)
    assert hist.to_dict()["bins"][1] == {"lower": 1.0, "upper": 2.0, "y": 3.0}


def test_histogram_flow_conv_tsne_modules():
    """The four UI modules beyond the train page (reference:
    module/{histogram,flow,convolutional,tsne}/*.java) serve real data."""
    import json as _json
    import urllib.request
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    ConvolutionLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.ui.listeners import (ConvolutionalIterationListener,
                                                 FlowIterationListener)

    conf = (NeuralNetConfiguration.builder().seed(2).updater(Sgd(0.1)).list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                    activation="relu", convolution_mode="same"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(6, 6, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    rng = np.random.default_rng(0)
    x = rng.random((8, 6, 6, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    net.set_listeners(StatsListener(storage, frequency=1, session_id="m1"),
                      ConvolutionalIterationListener(storage, x, frequency=1,
                                                     session_id="m1"))
    for _ in range(3):
        net.fit_batch(DataSet(x, y))

    server = UIServer(port=0).attach(storage).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def get(path):
            with urllib.request.urlopen(base + path, timeout=30) as r:
                return _json.loads(r.read())

        h = get("/weights/data?sid=m1")
        assert h["param_histograms"], "no param histograms served"
        some = next(iter(h["param_histograms"].values()))
        assert len(some["bins"]) == 20
        assert any(v for v in h["mean_magnitudes"].values())

        f = get("/flow/info?sid=m1")
        names = [n["name"] for n in f["graph"]["nodes"]]
        assert names == ["0", "1"]
        assert f["graph"]["edges"] == [["0", "1"]]
        assert f["score"] is not None

        a = get("/activations/data?sid=m1")
        assert a["layers"], "no activation grids served"
        lay = next(iter(a["layers"].values()))
        assert lay["height"] == 6 and lay["width"] == 6
        assert len(lay["channels"]) >= 1
        flat = np.asarray(lay["channels"][0])
        assert flat.shape == (6, 6) and flat.max() <= 255

        # t-SNE module: upload then serve
        req = urllib.request.Request(
            base + "/tsne/upload",
            data=_json.dumps({"words": ["a", "b"],
                              "coords": [[0.0, 1.0], [2.0, 3.0]]}).encode())
        with urllib.request.urlopen(req, timeout=10) as r:
            assert _json.loads(r.read())["status"] == "ok"
        t = get("/tsne/coords")
        assert t["words"] == ["a", "b"] and t["coords"][1] == [2.0, 3.0]
    finally:
        server.stop()


def test_flow_iteration_listener_publishes_graph():
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.ui.listeners import FlowIterationListener
    conf = (NeuralNetConfiguration.builder().seed(2).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=4, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(FlowIterationListener(storage, frequency=1,
                                            session_id="fl1"))
    x = np.random.default_rng(1).random((4, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
    net.fit_batch(DataSet(x, y))
    st = storage.get_static_info("fl1")
    assert st["graph"]["nodes"][0]["type"] == "DenseLayer"


def test_sqlite_stats_storage_indexed_roundtrip(tmp_path):
    """Durable INDEXED storage (J7FileStatsStorage/MapDB analog): training
    writes through the listener, a fresh handle reads it back, and the
    (session_id, iteration) index serves range queries."""
    from deeplearning4j_tpu.ui import SqliteStatsStorage
    net, ds = _net_and_data(1)
    p = tmp_path / "stats.db"
    storage = SqliteStatsStorage(p)
    net.set_listeners(StatsListener(storage, session_id="sq"))
    for _ in range(5):
        net.fit_batch(ds)
    assert storage.count_updates("sq") == 5
    assert storage.list_session_ids() == ["sq"]
    assert storage.get_static_info("sq")["model_class"] == "MultiLayerNetwork"
    assert storage.get_latest_update("sq")["iteration"] == 5
    since = storage.get_updates_since("sq", 3)
    assert [u["iteration"] for u in since] == [4, 5]
    storage.close()
    # a fresh handle sees the durable state
    storage2 = SqliteStatsStorage(p)
    assert storage2.count_updates("sq") == 5
    assert len(storage2.get_all_updates("sq")) == 5
    storage2.close()


def test_sqlite_stats_storage_concurrent_reader_process(tmp_path):
    """WAL concurrent-reader story, actually concurrent: a SEPARATE process
    holds a READ-ONLY connection and polls while this process keeps writing
    (UI server tailing a live run). The reader must see monotonically
    growing consistent snapshots and the writer must never block."""
    import subprocess, sys, pathlib, time
    from deeplearning4j_tpu.ui import SqliteStatsStorage
    p = tmp_path / "live.db"
    storage = SqliteStatsStorage(p)
    storage.put_static_info({"session_id": "live", "type": "init",
                             "model_class": "M"})
    storage.put_update({"session_id": "live", "iteration": 1,
                        "timestamp": 1.0})
    # read-only URI connection: provably cannot write/DDL; polls for ~10s
    code = (
        "import sqlite3, time\n"
        "c = sqlite3.connect('file:%s?mode=ro', uri=True, timeout=30)\n"
        "counts = []\n"
        "for _ in range(600):\n"
        "    (n,) = c.execute('SELECT COUNT(*) FROM updates').fetchone()\n"
        "    counts.append(n)\n"
        "    if n >= 160: break\n"
        "    time.sleep(0.05)\n"
        "print(counts[0], counts[-1])\n"
        "assert counts == sorted(counts), 'snapshot went backwards'\n"
        % str(p))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    # keep WRITING while the reader polls (long enough — ~8s — that the
    # child is provably reading mid-stream); writer must never block
    for i in range(2, 161):
        storage.put_update({"session_id": "live", "iteration": i,
                            "timestamp": float(i)})
        time.sleep(0.05)
    out, err = proc.communicate(timeout=120)
    assert proc.returncode == 0, err
    first, last = map(int, out.split())
    assert last == 160, (first, last)  # reader observed the live writes
    assert first < last                # ...while they were happening
    storage.close()


def test_ui_server_over_sqlite_storage(tmp_path):
    """The UI server attaches to SqliteStatsStorage like any StatsStorage."""
    import json as _json
    import urllib.request
    from deeplearning4j_tpu.ui import SqliteStatsStorage, UIServer
    net, ds = _net_and_data(1)
    storage = SqliteStatsStorage(tmp_path / "ui.db")
    net.set_listeners(StatsListener(storage, session_id="u1"))
    for _ in range(2):
        net.fit_batch(ds)
    server = UIServer(port=0).attach(storage).start()
    try:
        with urllib.request.urlopen(
                server.url + "/train/sessions", timeout=10) as r:
            sessions = _json.loads(r.read())
        assert "u1" in sessions
    finally:
        server.stop()


def test_ui_endpoints_serve_strict_json_with_nan_and_numpy():
    """GL002 regression: a stats payload carrying float('nan') and numpy
    scalars must serve 200 with VALID strict JSON (NaN -> null, np scalars
    -> numbers) on every UI endpoint — raw json.dumps would emit bare NaN,
    which json.loads(..., parse_constant=reject) and every strict decoder
    (JSON.parse, jq) refuse."""
    storage = InMemoryStatsStorage()
    server = UIServer(port=0).attach(storage).start()
    try:
        storage.put_update({
            "type": "stats", "session_id": "s-nan", "iteration": 0,
            "score": float("nan"),                       # diverged run
            "duration_ms": np.float32(3.5),              # numpy scalar
            "param_stats": {"w": {"mean_magnitude": np.float32("nan"),
                                  "mean": float("inf"),
                                  "histogram": [1, 2],
                                  "histogram_edges": [-1.0, 1.0]}},
            "memory": {"rss": np.int64(123)},
        })

        def reject(_):
            raise AssertionError("endpoint served bare NaN/Infinity")

        for path in ("/train/overview?sid=s-nan", "/train/model?sid=s-nan",
                     "/weights/data?sid=s-nan", "/flow/info?sid=s-nan"):
            with urllib.request.urlopen(server.url + path, timeout=30) as r:
                assert r.status == 200
                body = r.read().decode()
            d = json.loads(body, parse_constant=reject)   # strict-JSON check
            assert d["session"] == "s-nan"
        with urllib.request.urlopen(server.url + "/train/overview?sid=s-nan",
                                    timeout=30) as r:
            d = json.loads(r.read(), parse_constant=reject)
        assert d["scores"] == [None]                      # NaN -> null
        assert d["durations_ms"] == [3.5]                 # np.float32 -> num
        assert d["memory"]["rss"] == 123                  # np.int64 -> num
    finally:
        server.stop()


def test_stats_report_to_json_is_strict():
    """GL002 regression for the report serializers themselves (the payloads
    POSTed to /remoteReceive)."""
    from deeplearning4j_tpu.ui.stats import StatsReport
    r = StatsReport("s", 0, float("nan"),
                    param_stats={"w": {"max": np.float32("inf")}})
    d = json.loads(r.to_json(), parse_constant=lambda c: (_ for _ in ()).throw(
        AssertionError(f"bare {c} in report JSON")))
    assert d["score"] is None
    assert d["param_stats"]["w"]["max"] is None
