"""Streaming/serving tests (reference pattern: dl4j-streaming route tests —
consume records, run the model, assert published predictions)."""
import json
import urllib.request

import numpy as np

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd)
from deeplearning4j_tpu.streaming import (NDArrayMessage, serialize_array,
                                          deserialize_array, QueueSource,
                                          QueueSink, ServeRoute,
                                          InferenceServer)


def _net(nin=6, nout=3, seed=0):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())
    return MultiLayerNetwork(conf).init()


def test_serde_roundtrip():
    a = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    b = deserialize_array(serialize_array(a))
    np.testing.assert_array_equal(a, b)
    assert b.dtype == np.float32
    m = NDArrayMessage(a, {"id": "x1"})
    m2 = NDArrayMessage.from_json(m.to_json())
    np.testing.assert_array_equal(m2.array, a)
    assert m2.meta == {"id": "x1"}


def test_serve_route_publishes_predictions():
    net = _net()
    rng = np.random.default_rng(1)
    src, sink = QueueSource(), QueueSink()
    route = ServeRoute(net, src, sink, max_batch=16).start()
    inputs = [rng.normal(size=(2, 6)).astype(np.float32) for _ in range(5)]
    try:
        for i, x in enumerate(inputs):
            src.put(NDArrayMessage(x, {"id": i}))
        import time
        deadline = time.time() + 30
        while len(sink.messages) < 5 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        route.stop()
    assert len(sink.messages) == 5
    by_id = {m.meta["id"]: m.array for m in sink.messages}
    for i, x in enumerate(inputs):
        np.testing.assert_allclose(by_id[i], np.asarray(net.output(x)),
                                   rtol=1e-5, atol=1e-6)


def test_inference_server_http():
    net = _net()
    server = InferenceServer(net, port=0).start()
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    try:
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        np.testing.assert_allclose(np.asarray(out["prediction"]),
                                   np.asarray(net.output(x)), rtol=1e-5,
                                   atol=1e-6)
        assert out["shape"] == [4, 3]
        # serde-envelope body works too
        req = urllib.request.Request(
            server.url + "/predict", data=serialize_array(x).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out2 = json.loads(r.read())
        np.testing.assert_allclose(out2["prediction"], out["prediction"])
        with urllib.request.urlopen(server.url + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["served"] == 8
        # malformed body -> 400, server keeps serving
        req = urllib.request.Request(server.url + "/predict", data=b"notjson",
                                     headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()
