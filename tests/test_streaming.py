"""Streaming/serving tests (reference pattern: dl4j-streaming route tests —
consume records, run the model, assert published predictions)."""
import json
import urllib.request

import numpy as np

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, Sgd)
from deeplearning4j_tpu.streaming import (NDArrayMessage, serialize_array,
                                          deserialize_array, QueueSource,
                                          QueueSink, ServeRoute,
                                          InferenceServer)


def _net(nin=6, nout=3, seed=0):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())
    return MultiLayerNetwork(conf).init()


def test_serde_roundtrip():
    a = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    b = deserialize_array(serialize_array(a))
    np.testing.assert_array_equal(a, b)
    assert b.dtype == np.float32
    m = NDArrayMessage(a, {"id": "x1"})
    m2 = NDArrayMessage.from_json(m.to_json())
    np.testing.assert_array_equal(m2.array, a)
    assert m2.meta == {"id": "x1"}


def test_serve_route_publishes_predictions():
    net = _net()
    rng = np.random.default_rng(1)
    src, sink = QueueSource(), QueueSink()
    route = ServeRoute(net, src, sink, max_batch=16).start()
    inputs = [rng.normal(size=(2, 6)).astype(np.float32) for _ in range(5)]
    try:
        for i, x in enumerate(inputs):
            src.put(NDArrayMessage(x, {"id": i}))
        import time
        deadline = time.time() + 30
        while len(sink.messages) < 5 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        route.stop()
    assert len(sink.messages) == 5
    by_id = {m.meta["id"]: m.array for m in sink.messages}
    for i, x in enumerate(inputs):
        np.testing.assert_allclose(by_id[i], np.asarray(net.output(x)),
                                   rtol=1e-5, atol=1e-6)


def test_inference_server_http():
    net = _net()
    server = InferenceServer(net, port=0).start()
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    try:
        req = urllib.request.Request(
            server.url + "/predict",
            data=json.dumps({"data": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        np.testing.assert_allclose(np.asarray(out["prediction"]),
                                   np.asarray(net.output(x)), rtol=1e-5,
                                   atol=1e-6)
        assert out["shape"] == [4, 3]
        # serde-envelope body works too
        req = urllib.request.Request(
            server.url + "/predict", data=serialize_array(x).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out2 = json.loads(r.read())
        np.testing.assert_allclose(out2["prediction"], out["prediction"])
        with urllib.request.urlopen(server.url + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["served"] == 8
        # malformed body -> 400, server keeps serving
        req = urllib.request.Request(server.url + "/predict", data=b"notjson",
                                     headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()


def test_broker_route_over_real_socket():
    """VERDICT r3 #6: publish -> broker (TCP) -> ServeRoute -> broker ->
    consume, all over a real socket (NDArrayKafkaClient route analog)."""
    import time
    from deeplearning4j_tpu.streaming import (
        MessageBroker, BrokerClient, BrokerSource, BrokerSink, ServeRoute)
    broker = MessageBroker(port=0).start()
    try:
        net = _net()
        producer = BrokerClient(port=broker.port)
        consumer = BrokerClient(port=broker.port)
        route = ServeRoute(net, BrokerSource(BrokerClient(port=broker.port),
                                             "features"),
                           BrokerSink(BrokerClient(port=broker.port),
                                      "predictions"))
        route.start()
        try:
            rng = np.random.default_rng(0)
            xs = [rng.normal(size=(2, 6)).astype(np.float32)
                  for _ in range(5)]
            for i, x in enumerate(xs):
                producer.publish("features", json.loads(
                    NDArrayMessage(x, {"i": i}).to_json()))
            got = {}
            deadline = time.time() + 30
            while len(got) < 5 and time.time() < deadline:
                d = consumer.poll("predictions", timeout=1)
                if d is not None:
                    m = NDArrayMessage.from_json(d)
                    got[m.meta["i"]] = m.array
            assert len(got) == 5, f"only {len(got)}/5 predictions arrived"
            for i, x in enumerate(xs):
                np.testing.assert_allclose(got[i], np.asarray(net.output(x)),
                                           rtol=1e-5, atol=1e-6)
        finally:
            route.stop()
    finally:
        broker.stop()


def test_broker_client_reconnects_after_restart():
    """A broker restart (same port) must be invisible to the client: the
    request that hits the dead socket reconnects and retries."""
    from deeplearning4j_tpu.streaming import MessageBroker, BrokerClient
    broker = MessageBroker(port=0).start()
    port = broker.port
    client = BrokerClient(port=port, retries=40, retry_interval=0.1)
    try:
        client.publish("t", {"n": 1})
        assert client.poll("t")["n"] == 1
        broker.stop()
        broker = MessageBroker(port=port).start()  # restart on the same port
        client.publish("t", {"n": 2})              # must reconnect + retry
        assert client.poll("t", timeout=2)["n"] == 2
    finally:
        client.close()
        broker.stop()


def test_broker_unreachable_raises_after_retries():
    from deeplearning4j_tpu.streaming import BrokerClient
    import pytest as _pytest
    client = BrokerClient(port=1, retries=1, retry_interval=0.01)
    with _pytest.raises(ConnectionError, match="unreachable"):
        client.publish("t", {})


def test_broker_dead_letter_envelopes_over_socket():
    """A bad record mid-stream yields an error envelope on the prediction
    topic (Camel dead-letter analog) and the route keeps serving."""
    import time
    from deeplearning4j_tpu.streaming import (
        MessageBroker, BrokerClient, BrokerSource, BrokerSink, ServeRoute)
    broker = MessageBroker(port=0).start()
    try:
        net = _net()
        producer = BrokerClient(port=broker.port)
        consumer = BrokerClient(port=broker.port)
        route = ServeRoute(net, BrokerSource(BrokerClient(port=broker.port),
                                             "in"),
                           BrokerSink(BrokerClient(port=broker.port), "out"),
                           max_batch=1)
        route.start()
        try:
            rng = np.random.default_rng(1)
            producer.publish("in", json.loads(NDArrayMessage(
                rng.normal(size=(1, 999)).astype(np.float32),  # wrong width
                {"i": "bad"}).to_json()))
            producer.publish("in", json.loads(NDArrayMessage(
                rng.normal(size=(1, 6)).astype(np.float32),
                {"i": "good"}).to_json()))
            seen = {}
            deadline = time.time() + 30
            while len(seen) < 2 and time.time() < deadline:
                d = consumer.poll("out", timeout=1)
                if d is not None:
                    m = NDArrayMessage.from_json(d)
                    seen[m.meta["i"]] = m
            assert "error" in seen["bad"].meta
            assert seen["bad"].array.size == 0
            assert seen["good"].array.shape == (1, 3)
            assert "error" not in seen["good"].meta
        finally:
            route.stop()
    finally:
        broker.stop()


def test_broker_cross_process():
    """Broker in another PROCESS, client here: the route shape the reference
    runs against an external Kafka cluster."""
    import subprocess, sys, time
    from deeplearning4j_tpu.streaming import BrokerClient
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from deeplearning4j_tpu.streaming import MessageBroker\n"
        "import time\n"
        "b = MessageBroker(port=0).start()\n"
        "print(b.port, flush=True)\n"
        "time.sleep(60)\n" % (str(__import__('pathlib').Path(__file__).resolve().parents[1]),))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline().strip())
        client = BrokerClient(port=port)
        client.publish("xp", {"hello": "across processes"})
        assert client.poll("xp", timeout=5)["hello"] == "across processes"
        assert client.stats()["xp"] == 0
        client.close()
    finally:
        proc.kill()


def test_broker_publish_retry_is_idempotent():
    """A pub retried after a lost ok-response (same id) must not enqueue the
    record twice; a long client poll timeout is served by looped short
    server-side waits (never stranding a handler past the socket timeout)."""
    from deeplearning4j_tpu.streaming import MessageBroker, BrokerClient
    broker = MessageBroker(port=0).start()
    try:
        client = BrokerClient(port=broker.port)
        req = {"op": "pub", "topic": "idem", "msg": {"v": 1}, "id": "fixed"}
        assert client._request(req)["ok"]
        assert client._request(req).get("dup")  # simulated retry
        assert client.stats()["idem"] == 1
        assert client.poll("idem")["v"] == 1
        assert client.poll("idem", timeout=0.2) is None  # no duplicate
        # long-poll cap: timeout beyond MAX_POLL_S still returns (looped)
        import time
        t0 = time.monotonic()
        assert client.poll("idem", timeout=6.5) is None
        assert 6.0 < time.monotonic() - t0 < 12.0
    finally:
        broker.stop()


def test_broker_client_poll_deadline_reads_time_source():
    """GL001 regression: BrokerClient.poll's long-poll deadline reads the
    injected util.time_source clock — a ManualClock expires a 12s poll with
    zero real sleeps (each simulated broker round advances the clock)."""
    from deeplearning4j_tpu.streaming.broker import BrokerClient, MessageBroker
    from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                     TimeSourceProvider)
    clock = ManualClock()
    TimeSourceProvider.set_instance(clock)
    try:
        client = BrokerClient(port=1)   # never connected: _request is stubbed
        calls = []

        def fake_request(obj):
            calls.append(obj)
            # simulate the broker-side blocking wait by advancing manual time
            clock.advance(obj["timeout"] or 1.0)
            return {"msg": None}

        client._request = fake_request
        assert client.poll("t", timeout=12.0) is None
        # 12 manual seconds split into MAX_POLL_S-capped rounds: 5 + 5 + 2
        assert [c["timeout"] for c in calls] == \
            [MessageBroker.MAX_POLL_S, MessageBroker.MAX_POLL_S, 2.0]

        # a round that overshoots the deadline ends the poll immediately
        calls.clear()
        client._request = lambda obj: (calls.append(obj),
                                       clock.advance(100.0),
                                       {"msg": None})[-1]
        assert client.poll("t", timeout=3.0) is None
        assert len(calls) == 1
    finally:
        TimeSourceProvider.reset()


def test_broker_poll_frozen_manual_clock_does_not_hang():
    """A frozen ManualClock (installed but never advanced) must not turn a
    timed poll against a REAL broker into an infinite loop: once a round's
    real blocking wait served the full slice with zero injected-clock
    progress, poll returns None."""
    import time as _time
    from deeplearning4j_tpu.streaming.broker import BrokerClient, MessageBroker
    from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                     TimeSourceProvider)
    broker = MessageBroker(port=0).start()
    client = BrokerClient(port=broker.port)
    TimeSourceProvider.set_instance(ManualClock())
    try:
        t0 = _time.monotonic()
        assert client.poll("empty-topic", timeout=0.2) is None
        assert _time.monotonic() - t0 < 5.0       # bounded, not forever
    finally:
        TimeSourceProvider.reset()
        client.close()
        broker.stop()
