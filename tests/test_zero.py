"""ZeRO-1 sharded weight update & optimizer state (parallel/zero.py;
arXiv 2004.13336, ROADMAP item 4) on the 8-device virtual CPU mesh.

The contract under test: `ShardedTrainer(shard_update=True)` /
`ParallelWrapper(zero=True)` partition every updater-state tensor and the
parameter update over the data axis — reduce-scatter grads, per-shard optax
update, all-gather fresh params — with training math IDENTICAL to the
replicated update (f32 tolerance), per-device state bytes cut by the axis
size, donation intact (no "donated buffers were not usable" warnings), and
checkpoints that restore/reshard across replica-count changes.
"""
import os
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet, Adam,
                                Sgd)
from deeplearning4j_tpu.datasets.iterator.base import ListDataSetIterator
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)
from deeplearning4j_tpu.parallel.zero import ZeroUpdater, per_device_bytes
from jax.sharding import PartitionSpec as P


def _toy(n=64, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    y = np.argmax(X @ w, axis=1)
    return X, np.eye(nout, dtype=np.float32)[y]


def _conf(nin=8, nout=3, updater=None, seed=42, hidden=16):
    # hidden=16 -> param sizes 128/16/48/3: the [3] output bias does NOT
    # divide the 8-way data axis, so every run exercises the pad path
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())


def _graph_net(seed=5, updater=None):
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration as NNC
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
    gb = (NNC.builder().seed(seed).updater(updater or Adam(1e-2))
          .graph_builder().add_inputs("in"))
    gb.add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                    loss="MCXENT"), "d1")
    gb.set_outputs("out")
    gb.set_input_types(InputType.feed_forward(8))
    return ComputationGraph(gb.build()).init()


# ------------------------------------------------------------------- parity

def test_zero_bit_parity_multilayer_uneven_params():
    """Same seed, N steps: replicated vs ZeRO-sharded update produce
    identical params (f32 tolerance) — including the [3] output bias whose
    size does not divide the 8-way mesh (padding path)."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    a = MultiLayerNetwork(_conf()).init()
    for _ in range(5):
        a.fit_batch(ds)
    b = MultiLayerNetwork(_conf()).init()
    tr = ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True)
    for _ in range(5):
        tr.fit_batch(ds)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    # the moments really live sharded over the data axis between steps
    flat_specs = [l.sharding.spec for l in
                  jax.tree_util.tree_leaves(b.opt_state)
                  if getattr(l, "ndim", 0) >= 1]
    assert flat_specs and all(s == P("data") for s in flat_specs)


def test_zero_bit_parity_computation_graph():
    X, Y = _toy()
    ds = DataSet(X, Y)
    a = _graph_net()
    for _ in range(5):
        a.fit_batch(ds)
    b = _graph_net()
    tr = ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True)
    for _ in range(5):
        tr.fit_batch(ds)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_zero_scanned_multistep_parity():
    """fit(steps_per_execution=K) compiles K ZeRO-sharded steps into ONE
    scanned executable — params must still match the single-device run."""
    sets = [DataSet(*_toy(n=32, seed=s)) for s in range(8)]
    a = MultiLayerNetwork(_conf()).init()
    for ds in sets:
        a.fit_batch(ds)
    b = MultiLayerNetwork(_conf()).init()
    tr = ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True)
    tr.fit(ListDataSetIterator(sets), steps_per_execution=4)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    assert b.iteration_count == 8


def test_zero_tbptt_parity_and_donation_clean():
    """Both TBPTT paths (per-window fit_batch and the scanned multi_tbptt
    executable) run with the ZeRO update — identical params to the
    replicated run, zero donation warnings (the sharded state leaves keep
    identical shapes across the step, so aliasing still sticks)."""
    from deeplearning4j_tpu.zoo.models import char_rnn_lstm

    def mk():
        return char_rnn_lstm(vocab_size=12, hidden=16, layers=2,
                             tbptt=5).init()

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(8, 21))
    x = np.eye(12, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(12, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))

    a = mk()
    a.fit_batch(ds)
    plan_a = a.prepare_steps([ds] * 2)
    assert plan_a is not None and plan_a[0] == "tbptt"
    a.fit_prepared(plan_a)

    b = mk()
    b.set_update_sharding(ZeroUpdater(make_mesh(n_data=8)))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        b.fit_batch(ds)                       # per-window tbptt path
        plan_b = b.prepare_steps([ds] * 2)
        assert plan_b is not None and plan_b[0] == "tbptt"
        b.fit_prepared(plan_b)                # scanned multi_tbptt path
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation
    for pa, pb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=1e-5, atol=1e-6)


def test_zero_std_paths_no_donation_warnings():
    """ISSUE acceptance: the ZeRO std step and the scanned multistep must
    not trip "Some donated buffers were not usable" — HBM bytes are the
    whole point of the transform."""
    sets = [DataSet(*_toy(n=32, seed=s)) for s in range(4)]
    net = MultiLayerNetwork(_conf()).init()
    tr = ShardedTrainer(net, mesh=make_mesh(n_data=8), shard_update=True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        tr.fit_batch(sets[0])                              # std jit step
        tr.fit(ListDataSetIterator(sets), steps_per_execution=4)  # scanned
    donation = [str(w.message) for w in caught
                if "donated buffers were not usable" in str(w.message)]
    assert donation == [], donation


def test_zero_tensor_parallel_layer_excluded_first_match():
    """A layer carrying a tensor-parallel spec under the first-match
    ShardingRules keeps its ordinary per-layer update (moments mirror the
    TP param shardings); the remaining layers zero-shard — and the math
    still matches the single-device run."""
    X, Y = _toy(n=32)
    ds = DataSet(X, Y)
    a = MultiLayerNetwork(_conf(seed=7)).init()
    a.fit_batch(ds)

    b = MultiLayerNetwork(_conf(seed=7)).init()
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    tr = ShardedTrainer(b, mesh=mesh, rules=rules, shard_update=True)
    assert not tr.zero.included("0", b.params["0"])
    assert tr.zero.included("1", b.params["1"])
    tr.fit_batch(ds)
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    # excluded layer's W-moment mirrors the TP sharding; included layer's
    # moments are flat data-axis shards
    leaves = jax.tree_util.tree_flatten_with_path(b.opt_state)[0]
    specs = {}
    for path, leaf in leaves:
        if hasattr(leaf, "sharding"):
            specs[jax.tree_util.keystr(path)] = leaf.sharding.spec
    tp = [s for k, s in specs.items() if k.startswith("['0'") and "'W'" in k
          and s == P(None, "model")]
    flat = [s for k, s in specs.items() if k.startswith("['1'")
            and s == P("data")]
    assert tp and flat, specs


def test_parallel_wrapper_zero_facade_trains():
    X, Y = _toy(n=256)
    from deeplearning4j_tpu import INDArrayDataSetIterator
    from deeplearning4j_tpu.parallel.parallel_wrapper import ParallelWrapper
    net = MultiLayerNetwork(_conf()).init()
    pw = (ParallelWrapper.builder(net).workers(8).zero(True).build())
    s0 = net.score(DataSet(X, Y))
    pw.fit(INDArrayDataSetIterator(X, Y, 64), epochs=5)
    assert net.score(DataSet(X, Y)) < s0


# ------------------------------------------------------- bytes & telemetry

def test_zero_state_bytes_at_least_4x_smaller_and_gauges_report():
    """ISSUE acceptance: with 8 devices on the data axis, per-device
    optimizer-state bytes drop >= 4x vs replicated (Adam: ~8x minus
    padding), and the telemetry gauges carry the attribution."""
    net_r = MultiLayerNetwork(_conf(hidden=128)).init()
    ShardedTrainer(net_r, mesh=make_mesh(n_data=8))
    repl = per_device_bytes(net_r.opt_state)

    net_z = MultiLayerNetwork(_conf(hidden=128)).init()
    ShardedTrainer(net_z, mesh=make_mesh(n_data=8), shard_update=True)
    sharded = per_device_bytes(net_z.opt_state)
    assert sharded * 4 <= repl, (sharded, repl)
    # params stay replicated (the forward consumes them everywhere)
    assert per_device_bytes(net_z.params) == per_device_bytes(net_r.params)

    from deeplearning4j_tpu.telemetry.registry import get_registry
    series = {}
    for labels, value in get_registry().gauge(
            "opt_state_bytes_per_device").series():
        series[labels.get("mode")] = value
    assert series["zero"] == sharded
    assert series["replicated"] == repl
    assert get_registry().gauge("param_bytes_per_device").series()


# ---------------------------------------------------------- checkpointing

def test_zero_zip_checkpoint_reshards_replica_count_change(tmp_path):
    """ModelSerializer zips store CANONICAL (per-param, unpadded) updater
    state: a run checkpointed at 8 shards restores into a plain model and
    resumes at 4 shards with momentum intact — params match an
    uninterrupted single-device run."""
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    for _ in range(6):
        oracle.fit_batch(ds)

    b = MultiLayerNetwork(_conf()).init()
    ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True) \
        .fit(ListDataSetIterator([ds] * 3))
    path = str(tmp_path / "zero.zip")
    ModelSerializer.write_model(b, path)

    restored = ModelSerializer.restore(path)
    # canonical layout: every >=1-D opt leaf has a param's exact shape
    pshapes = {tuple(l.shape) for l in
               jax.tree_util.tree_leaves(restored.params)}
    for leaf in jax.tree_util.tree_leaves(restored.opt_state):
        if getattr(leaf, "ndim", 0) >= 1:
            assert tuple(leaf.shape) in pshapes
    tr4 = ShardedTrainer(restored,
                         mesh=make_mesh(n_data=4, devices=jax.devices()[:4]),
                         shard_update=True)
    for _ in range(3):
        tr4.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               restored.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_zero_orbax_sharded_checkpoint_roundtrip(tmp_path):
    """The orbax tensor-store format stores canonical updater state too, so
    save_sharded/restore_sharded round-trips a ZeRO run and re-shards on
    resume."""
    from deeplearning4j_tpu.util.sharded_checkpoint import (save_sharded,
                                                            restore_sharded)
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    for _ in range(5):
        oracle.fit_batch(ds)

    b = MultiLayerNetwork(_conf()).init()
    tr = ShardedTrainer(b, mesh=make_mesh(n_data=8), shard_update=True)
    for _ in range(3):
        tr.fit_batch(ds)
    save_sharded(b, tmp_path / "ck")
    restored = restore_sharded(tmp_path / "ck")
    tr2 = ShardedTrainer(restored, mesh=make_mesh(n_data=8),
                         shard_update=True)
    for _ in range(2):
        tr2.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               restored.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_fault_tolerant_trainer_resumes_zero_run_on_fewer_replicas(tmp_path):
    """FaultTolerantTrainer drives a ShardedTrainer(zero) unchanged: the
    checkpoint zips hold the INNER network with canonical state; a restart
    whose factory builds a 4-replica trainer adopts the 8-replica
    checkpoint, re-shards, fast-forwards, and lands on the uninterrupted
    run's params."""
    from deeplearning4j_tpu.train.fault_tolerance import (CheckpointConfig,
                                                          FaultTolerantTrainer)
    X, Y = _toy()
    ds = DataSet(X, Y)
    ckdir = str(tmp_path / "ck")

    t1 = FaultTolerantTrainer(
        lambda: ShardedTrainer(MultiLayerNetwork(_conf()).init(),
                               mesh=make_mesh(n_data=8), shard_update=True),
        CheckpointConfig(ckdir, frequency=2))
    assert not t1.resumed
    t1.fit(ListDataSetIterator([ds] * 4), epochs=1)        # iterations 1..4

    t2 = FaultTolerantTrainer(
        lambda: ShardedTrainer(MultiLayerNetwork(_conf()).init(),
                               mesh=make_mesh(n_data=4,
                                              devices=jax.devices()[:4]),
                               shard_update=True),
        CheckpointConfig(ckdir, frequency=2))
    assert t2.resumed
    t2.fit(ListDataSetIterator([ds] * 4), epochs=2)        # iterations 5..8

    oracle = MultiLayerNetwork(_conf()).init()
    for _ in range(8):
        oracle.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               t2._net().get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    assert t2._net().iteration_count == 8


def test_plain_trainer_after_zero_trainer_reverts_to_replicated():
    """shard_update=False means REPLICATED: wrapping a previously
    ZeRO-trained model in a plain ShardedTrainer (even on a DIFFERENT mesh
    size) must convert the updater state back to canonical instead of
    crashing on the stale mesh placement — and keep training to parity."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    for _ in range(4):
        oracle.fit_batch(ds)

    net = MultiLayerNetwork(_conf()).init()
    tr8 = ShardedTrainer(net, mesh=make_mesh(n_data=8), shard_update=True)
    for _ in range(2):
        tr8.fit_batch(ds)
    assert net._zero is not None
    tr4 = ShardedTrainer(net, mesh=make_mesh(n_data=4,
                                             devices=jax.devices()[:4]))
    assert net._zero is None
    # canonical again: every >=1-D opt leaf has a param's exact shape
    pshapes = {tuple(l.shape) for l in jax.tree_util.tree_leaves(net.params)}
    for leaf in jax.tree_util.tree_leaves(net.opt_state):
        if getattr(leaf, "ndim", 0) >= 1:
            assert tuple(leaf.shape) in pshapes
    for _ in range(2):
        tr4.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_zero_tx_honors_partial_update_contract():
    """per_layer_transform.update accepts a SUBSET of layers (PipelineTrainer
    updates one stage's layers at a time with single-layer dicts); the ZeRO
    wrap must preserve that contract instead of KeyError-ing on absent
    layers."""
    net = MultiLayerNetwork(_conf()).init()
    net.set_update_sharding(ZeroUpdater(make_mesh(n_data=8)))
    grads = jax.tree_util.tree_map(jnp.ones_like, net.params)
    ups, new_state = net._tx.update({"1": grads["1"]},
                                    {"1": net.opt_state["1"]},
                                    {"1": net.params["1"]})
    assert set(ups) == {"1"} and set(new_state) == {"1"}
    for k, u in ups["1"].items():
        assert u.shape == net.params["1"][k].shape


# ----------------------------------------------------- re-shard edge cases
# (elastic-fleet satellites: ElasticTrainer re-shards a LIVE run through
# set_update_sharding — canonical conversion must be bit-exact through the
# degenerate single-shard mesh, growth past the original shard count, and
# chains of consecutive re-shards.)

def _canonical_moments(net):
    """Canonical (per-param) updater state as a flat {path: np.array}."""
    st = net.opt_state
    z = getattr(net, "_zero", None)
    if z is not None:
        st = z.to_canonical(st, net.params)
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(st)[0]:
        if hasattr(leaf, "shape"):
            out["/".join(str(k) for k in path)] = np.asarray(leaf)
    return out


def _reshard(net, n):
    devs = jax.devices()[:n]
    return ShardedTrainer(net, mesh=make_mesh(n_data=n, devices=devs),
                          shard_update=True)


def _assert_moments_bitwise(net, oracle):
    a, b = _canonical_moments(net), _canonical_moments(oracle)
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_zero_reshard_shrink_to_single_shard_degenerate():
    """8 shards -> 1 (the degenerate mesh: no partitioning at all) keeps
    every moment BIT-identical to a never-resharded run, and training
    continues producing the same params."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    otr = ShardedTrainer(oracle, mesh=make_mesh(n_data=8), shard_update=True)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(4):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 1)
    _assert_moments_bitwise(net, oracle)
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(), rtol=1e-5, atol=1e-6)


def test_zero_reshard_grow_past_original_count():
    """2 shards -> 8 (more shards than the run ever had: every flat moment
    re-pads to the larger multiple, incl. the [3] bias padding 4 -> 8):
    moments stay bit-identical, training parity holds."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    otr = ShardedTrainer(oracle, mesh=make_mesh(n_data=2,
                                                devices=jax.devices()[:2]),
                         shard_update=True)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 2)
    for _ in range(4):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 8)
    _assert_moments_bitwise(net, oracle)
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(), rtol=1e-5, atol=1e-6)


def test_zero_two_consecutive_reshards_bit_parity():
    """8 -> 4 -> 8 back to back (no steps in between): the canonical
    conversion CHAIN is bit-exact — two consecutive re-shards leave every
    moment identical to the never-resharded run's."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    otr = ShardedTrainer(oracle, mesh=make_mesh(n_data=8), shard_update=True)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(4):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 4)          # replica loss...
    tr = _reshard(net, 8)          # ...immediately regained
    _assert_moments_bitwise(net, oracle)
    for _ in range(2):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(), rtol=1e-5, atol=1e-6)


def test_zero_reshards_with_training_between_f32_parity():
    """The full elastic lose-then-regain arc WITH steps at each topology
    (8 -> 4 -> 8): moments cannot stay bitwise across a different
    all-reduce tree, but params and canonical moments track the fixed-
    topology run within f32 tolerance — momentum is intact, not reset."""
    X, Y = _toy()
    ds = DataSet(X, Y)
    oracle = MultiLayerNetwork(_conf()).init()
    otr = ShardedTrainer(oracle, mesh=make_mesh(n_data=8), shard_update=True)
    net = MultiLayerNetwork(_conf()).init()
    tr = _reshard(net, 8)
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 4)          # replica loss
    for _ in range(3):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    tr = _reshard(net, 8)          # replicas regained
    for _ in range(2):
        otr.fit_batch(ds)
        tr.fit_batch(ds)
    a, b = _canonical_moments(net), _canonical_moments(oracle)
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
    np.testing.assert_allclose(oracle.get_flat_params(),
                               net.get_flat_params(), rtol=1e-5, atol=1e-6)
