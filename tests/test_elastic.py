"""Elastic fleet subsystem (deeplearning4j_tpu/elastic/): preemption-
tolerant training, the serving autoscaler, the ReplicaLauncher SPI, and
the open-loop load generator.

The acceptance scenarios from the elastic ISSUE run LIVE here:
- a chaos FaultPlan kills a training replica mid-run; the run re-shards
  ZeRO state to the survivors and finishes with final-param parity vs an
  uninterrupted run (zero checkpoint-and-halt restarts);
- the ManualClock autoscale smoke (tools/smoke_elastic.py): ramp ->
  scale 1->3 -> preemption -> zero client 5xx -> drain back to 1, every
  transition visible on /fleet/* and the trace-correlated logs.
"""
import json
import tempfile

import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd)
from deeplearning4j_tpu.elastic import (AutoscaleController, AutoscalePolicy,
                                        ElasticTrainer, InProcessLauncher,
                                        MembershipView)
from deeplearning4j_tpu.parallel.sharding import ShardedTrainer, make_mesh
from deeplearning4j_tpu.resilience import FaultPlan, FaultRule
from deeplearning4j_tpu.telemetry.health import HealthMonitor
from deeplearning4j_tpu.train import CheckpointConfig
from deeplearning4j_tpu.util.time_source import (ManualClock,
                                                 TimeSourceProvider)


@pytest.fixture
def clock():
    c = ManualClock(start_s=1000.0)
    TimeSourceProvider.set_instance(c)
    yield c
    TimeSourceProvider.reset()


def _factory(seed=11):
    def make():
        conf = (NeuralNetConfiguration.builder()
                .seed(seed).updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="MCXENT"))
                .input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf)
    return make


def _data(n=80, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    return X, Y


# ---------------------------------------------------------------- membership

def test_membership_heartbeat_ttl_and_kill_revive(clock):
    view = MembershipView(["w0", "w1", "w2"], ttl_s=10.0)
    assert view.alive() == ["w0", "w1", "w2"]
    v0 = view.version
    # silence past the ttl = dead, no explicit signal needed
    clock.advance(5.0)
    view.heartbeat("w0")
    view.heartbeat("w1")
    clock.advance(6.0)
    assert view.alive() == ["w0", "w1"]
    # explicit preemption beats a fresh heartbeat
    assert view.kill("w1") is True
    assert view.kill("w1") is False          # idempotent
    view.heartbeat("w1")                     # straggler beat is ignored
    assert view.alive() == ["w0"]
    assert view.version > v0
    # revive brings it back with a fresh beat
    assert view.revive("w1") is True
    assert view.revive("w1") is False        # already alive + fresh
    assert view.alive() == ["w0", "w1"]
    st = view.status()
    assert st["members"]["w2"]["alive"] is False
    assert st["members"]["w1"]["killed"] is False
    with pytest.raises(KeyError):
        view.revive("nope")


def test_preempt_rule_round_trip_and_poll(clock):
    plan = FaultPlan([FaultRule("preempt", target="w2", at_step=5,
                                cooldown_s=30.0, name="p")])
    # JSON round-trip preserves the preempt fields
    plan = FaultPlan.from_json(json.loads(json.dumps(plan.to_json())))
    [d] = plan.to_json()
    assert d == {"kind": "preempt", "name": "p", "target": "w2",
                 "at_step": 5, "cooldown_s": 30.0}
    assert plan.poll_preemptions(4) == []
    [kill] = plan.poll_preemptions(5)
    assert kill == {"action": "kill", "target": "w2", "rule": "p",
                    "step": 5}
    assert plan.poll_preemptions(6) == []    # fires exactly once
    clock.advance(29.0)
    assert plan.poll_preemptions(7) == []    # cooldown not elapsed
    clock.advance(1.0)
    [rev] = plan.poll_preemptions(8)
    assert rev["action"] == "revive" and rev["target"] == "w2"
    assert plan.poll_preemptions(9) == []    # revive fires exactly once
    assert plan.injected() == {"p": 1}
    # preempt rules never touch the HTTP interceptor
    assert plan.intercept("POST", "http://x/predict", 1.0) is None


def test_preempt_rule_validation():
    with pytest.raises(ValueError):
        FaultRule("preempt", name="no-target", at_step=3)
    with pytest.raises(ValueError):
        FaultRule("preempt", target="w0", name="no-step")


# ---------------------------------------------------------- elastic training

def test_chaos_preemption_reshards_and_matches_uninterrupted(tmp_path):
    """THE acceptance scenario: a FaultPlan preempt rule kills replica w3
    at step 10 of a 4-replica ZeRO run; training re-shards to the three
    survivors in-process and finishes with final params matching an
    uninterrupted 4-replica run (f32 tolerance) — momentum intact, zero
    checkpoint-and-halt restarts."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)

    ref_net = _factory()()
    ref = ShardedTrainer(ref_net,
                         mesh=make_mesh(n_data=4, devices=jax.devices()[:4]),
                         shard_update=True)
    ref.fit(it, epochs=2)

    plan = FaultPlan([FaultRule("preempt", target="w3", at_step=10,
                                name="kill-w3")])
    monitor = HealthMonitor()
    trainer = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ck",
                                                          frequency=0),
                             devices=jax.devices()[:4], plan=plan,
                             monitor=monitor)
    assert not trainer.resumed
    trainer.fit(it, epochs=2)

    assert trainer.reshards == 1
    assert trainer._alive == ["w0", "w1", "w2"]
    assert plan.injected() == {"kill-w3": 1}
    assert [e["action"] for e in trainer.preemption_events] == ["kill"]
    np.testing.assert_allclose(ref_net.get_flat_params(),
                               trainer._net().get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    # zero restarts: nothing ever restored, nothing quarantined
    import os
    assert not trainer.resumed
    assert not any(n.startswith("halt-")
                   for n in os.listdir(tmp_path / "ck"))
    # the run is visible to the health/fleet plane, with elastic detail
    report = monitor.check()
    comp = report["components"][trainer.health_key]
    assert comp["status"] == "healthy"
    assert comp["iteration"] == 20 and comp["replicas"] == 3
    assert comp["membership"]["members"]["w3"]["killed"] is True


def test_elastic_regain_reshards_up_and_training_continues(tmp_path):
    """Replica loss then regain: kill + revive via the membership view
    across epochs — the trainer re-shards down then back up and keeps
    training (momentum carried through both hops)."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    trainer = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ck",
                                                          frequency=0),
                             devices=jax.devices()[:4],
                             monitor=HealthMonitor())
    trainer.membership.kill("w2")
    trainer.fit(it, epochs=1)
    assert trainer.reshards == 1 and len(trainer._alive) == 3
    trainer.membership.revive("w2")
    trainer.fit(it, epochs=2)
    assert trainer.reshards == 2 and len(trainer._alive) == 4
    assert trainer.state["iteration"] == 20
    assert np.isfinite(trainer._net().score_value)


def test_elastic_below_min_replicas_checkpoints_and_raises(tmp_path):
    from deeplearning4j_tpu.elastic import ElasticImpossible
    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    plan = FaultPlan([
        FaultRule("preempt", target="w0", at_step=2, name="k0"),
        FaultRule("preempt", target="w1", at_step=2, name="k1")])
    trainer = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ck",
                                                          frequency=0),
                             devices=jax.devices()[:2], plan=plan,
                             min_replicas=2, monitor=HealthMonitor())
    with pytest.raises(ElasticImpossible):
        trainer.fit(it, epochs=1)
    # the final checkpoint landed before the raise: a fresh trainer resumes
    t2 = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ck",
                                                     frequency=0),
                        devices=jax.devices()[:2],
                        monitor=HealthMonitor())
    assert t2.resumed and t2.state["iteration"] == 2


def test_elastic_checkpoint_resume_at_new_topology(tmp_path):
    """An ElasticTrainer checkpoint restores into a trainer built for a
    DIFFERENT replica count (the canonical-state re-shard on adopt)."""
    X, Y = _data()
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    ck = CheckpointConfig(tmp_path / "ck", frequency=7)
    t1 = ElasticTrainer(_factory(), ck, devices=jax.devices()[:4],
                        monitor=HealthMonitor())
    t1.fit(it, epochs=1)
    t2 = ElasticTrainer(_factory(), ck, devices=jax.devices()[:2],
                        monitor=HealthMonitor())
    assert t2.resumed and t2.state["iteration"] == 10
    t2.fit(it, epochs=2)
    ref = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ref",
                                                      frequency=0),
                         devices=jax.devices()[:4],
                         monitor=HealthMonitor())
    ref.fit(it, epochs=2)
    np.testing.assert_allclose(ref._net().get_flat_params(),
                               t2._net().get_flat_params(),
                               rtol=1e-5, atol=1e-6)


def test_elastic_external_view_ttl_staleness_reshards(tmp_path):
    """Regression (review finding): with an EXTERNAL membership view —
    somebody else beats — a member going silent past the ttl must re-shard
    even though staleness bumps no version counter. The poll diffs the
    alive set itself."""
    from deeplearning4j_tpu.util.time_source import monotonic_s
    X, Y = _data(n=40)
    it = ListDataSetIterator(DataSet(X, Y), batch_size=8)
    view = MembershipView(["w0", "w1", "w2", "w3"], ttl_s=3600.0)
    trainer = ElasticTrainer(_factory(), CheckpointConfig(tmp_path / "ck",
                                                          frequency=0),
                             devices=jax.devices()[:4], membership=view,
                             monitor=HealthMonitor())

    beats = {"skip": set()}
    orig_before = trainer.poll_membership

    def beat_then_poll():
        # the "external system": beats every member except the silenced
        # ones; nothing ever calls kill(), so version never changes
        for n in view.members():
            if n not in beats["skip"]:
                view.heartbeat(n)
        if trainer.state["iteration"] == 2:
            beats["skip"].add("w3")
            view._beats["w3"] = monotonic_s() - 7200.0   # long silent
        return orig_before()
    trainer._before_batch = beat_then_poll

    trainer.fit(it, epochs=1)
    assert trainer.reshards == 1
    assert trainer._alive == ["w0", "w1", "w2"]


# ------------------------------------------------------------- policy JSON

def test_autoscale_policy_round_trip_and_validation():
    p = AutoscalePolicy(min_replicas=1, max_replicas=4, step=2,
                        cooldown_s=30.0, for_duration_s=5.0, window_s=60.0,
                        scale_up={"queue_depth": 16, "shed_ratio": 0.1},
                        scale_down={"queue_depth": 1})
    q = AutoscalePolicy.from_dict(json.loads(json.dumps(p.to_dict())))
    assert q.to_dict() == p.to_dict()
    up, down = q.rules()
    assert {r.name for r in up} == {"autoscale_up_queue_depth",
                                    "autoscale_up_shed_ratio"}
    assert [r.name for r in down] == ["autoscale_down_queue_depth"]
    assert all(r.for_duration_s == 5.0 for r in up + down)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(scale_up={"bogus_signal": 1})


# ------------------------------------------------- launcher + frontend pool

def _write_zip(path, seed=0, nin=6):
    from tools.smoke_telemetry import _tiny_net
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    ModelSerializer.write_model(_tiny_net(nin=nin, seed=seed), str(path))


def test_inprocess_launcher_warm_launch_and_max_guard(tmp_path):
    from deeplearning4j_tpu.util.http import get_json, post_json
    _write_zip(tmp_path / "v1.zip")
    launcher = InProcessLauncher(
        scan_dir=str(tmp_path), max_replicas=2,
        server_opts=dict(alert_interval_s=0),
        deploy_event={"kind": "deploy", "version": "v1"})
    try:
        url = launcher.launch("r0")
        # came up WARM: the deploy event replayed through the
        # RegistrySubscriber path before launch() returned
        models = get_json(url + "/models", timeout=30)
        assert models["active"] == "v1"
        res = post_json(url + "/predict", {"data": [[0.1] * 6]}, timeout=30)
        assert res["version"] == "v1"
        launcher.launch("r1")
        assert launcher.names() == ["r0", "r1"]
        # THE bound: a third spawn hits the max_replicas wall
        with pytest.raises(RuntimeError):
            launcher.launch("r2")
        with pytest.raises(ValueError):
            launcher.launch("r0")            # duplicate name
        launcher.drain("r1")
        assert launcher.names() == ["r0"] and not launcher.alive("r1")
    finally:
        launcher.close()


def test_launcher_broker_fan_deploy_reaches_every_replica(tmp_path):
    """Deploy fan-out over the broker RegistrySubscriber path: a fan_deploy
    publishes to each replica's own topic (competing-consumer queues need
    per-replica topics) and every replica applies it."""
    from deeplearning4j_tpu.streaming.broker import BrokerClient, MessageBroker
    _write_zip(tmp_path / "v1.zip", seed=0)
    _write_zip(tmp_path / "v2.zip", seed=1)
    broker = MessageBroker(port=0).start()
    launcher = InProcessLauncher(
        scan_dir=str(tmp_path), max_replicas=3,
        server_opts=dict(alert_interval_s=0),
        broker_factory=lambda: BrokerClient(port=broker.port, retries=3),
        deploy_event={"kind": "deploy", "version": "v1"})
    try:
        launcher.launch("a")
        launcher.launch("b")
        assert launcher.fan_deploy({"kind": "deploy", "version": "v2"}) == 2
        deadline = 50
        import time
        for _ in range(deadline):
            active = {n: launcher.server(n).registry.active_version
                      for n in ("a", "b")}
            if set(active.values()) == {"v2"}:
                break
            time.sleep(0.1)
        assert set(active.values()) == {"v2"}, active
        assert launcher.fan_errors == []
        # the NEXT launch warms straight to the newest event
        url = launcher.launch("c")
        assert launcher.server("c").registry.active_version == "v2"
        assert url
    finally:
        launcher.close()
        broker.stop()


def test_frontend_add_remove_replica_routes_and_probes(tmp_path):
    from deeplearning4j_tpu.serving import FleetFrontend, ServingServer
    from deeplearning4j_tpu.util.http import post_json
    from tools.smoke_telemetry import _tiny_net
    s1 = ServingServer(_tiny_net(), version="v1", alert_interval_s=0).start()
    s2 = ServingServer(_tiny_net(), version="v1", alert_interval_s=0).start()
    fe = FleetFrontend([s1.url], names=["a"], health_interval_s=1e9,
                       alert_interval_s=0).start()
    try:
        body = {"data": [[0.1] * 6]}
        assert post_json(fe.url + "/predict", body, timeout=30)["replica"] \
            == "a"
        fe.add_replica(s2.url, name="b")
        assert "replica:b" in fe.health.components()
        seen = {post_json(fe.url + "/predict", body, timeout=30)["replica"]
                for _ in range(6)}
        assert seen == {"a", "b"}
        with pytest.raises(ValueError):
            fe.add_replica(s2.url, name="b")
        fe.remove_replica("b")
        assert "replica:b" not in fe.health.components()
        seen = {post_json(fe.url + "/predict", body, timeout=30)["replica"]
                for _ in range(4)}
        assert seen == {"a"}
        with pytest.raises(ValueError):
            fe.remove_replica("a")           # never empty the pool
        with pytest.raises(KeyError):
            fe.remove_replica("ghost")
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


def test_frontend_forwards_pool_wide_shed_as_429():
    """Admission backpressure stays 429 at the frontend (not a dressed-up
    502): with every replica shedding, the client sees the real status."""
    import urllib.error
    from deeplearning4j_tpu.serving import FleetFrontend, ServingServer
    from deeplearning4j_tpu.util.http import post_json
    from tools.smoke_telemetry import _tiny_net
    server = ServingServer(_tiny_net(), version="v1",
                           alert_interval_s=0).start()
    fe = FleetFrontend([server.url], health_interval_s=1e9,
                       alert_interval_s=0).start()
    try:
        # the replica sheds every /predict (admission 429), stays healthy
        plan = FaultPlan([FaultRule("error", match=server.url + "/predict",
                                    status=429, name="shed")])
        with plan:
            with pytest.raises(urllib.error.HTTPError) as ei:
                post_json(fe.url + "/predict", {"data": [[0.1] * 6]},
                          timeout=30)
        assert ei.value.code == 429
        body = json.loads(ei.value.read() or b"{}")
        assert body.get("attempts", 1) >= 1
    finally:
        fe.stop()
        server.stop()


# -------------------------------------------------------------- autoscaler

def test_autoscaler_scale_up_down_on_injected_signals(tmp_path, clock):
    """Clock-driven controller arc without load: the queue-depth gauge is
    fed by a stub replica /metrics, so the AlertEngine lifecycle (pending
    -> firing with for_duration damping), cooldown gating, and the
    launcher round-trip are all assertable deterministically."""
    from deeplearning4j_tpu.serving import FleetFrontend
    _write_zip(tmp_path / "v1.zip")
    launcher = InProcessLauncher(
        scan_dir=str(tmp_path), max_replicas=3,
        server_opts=dict(alert_interval_s=0),
        deploy_event={"kind": "deploy", "version": "v1"})
    fe = None
    try:
        url0 = launcher.launch("r0")
        fe = FleetFrontend([url0], names=["r0"], health_interval_s=1e9,
                           alert_interval_s=0).start()
        policy = AutoscalePolicy(
            min_replicas=1, max_replicas=3, step=1, cooldown_s=10.0,
            for_duration_s=0.0, window_s=60.0,
            scale_up={"queue_depth": 4.0},
            scale_down={"queue_depth": 0.5})
        events = []
        ctl = AutoscaleController(fe, launcher, policy,
                                  sinks=[events.append], interval_s=0)
        # stub the collected queue depth: the decision plumbing under test
        # is gauge -> rule -> action, not the scrape
        depth = {"v": 0.0}
        orig = ctl.collect_signals

        def collect():
            out = orig()
            ctl._g_queue.set(depth["v"])
            out["queue_depth"] = depth["v"]
            return out
        ctl.collect_signals = collect

        assert ctl.evaluate()["action"] is None
        depth["v"] = 9.0
        r = ctl.evaluate()
        assert r["action"] == "scale_up"
        assert len(fe.replicas) == 2
        # cooldown gates the next hop until the clock passes it
        assert ctl.evaluate()["action"] is None
        clock.advance(11.0)
        assert ctl.evaluate()["action"] == "scale_up"
        assert len(fe.replicas) == 3
        clock.advance(11.0)
        assert ctl.evaluate()["action"] is None   # at max_replicas
        # load drops -> drain one per cooldown window, down to min
        depth["v"] = 0.0
        clock.advance(11.0)
        assert ctl.evaluate()["action"] == "scale_down"
        assert len(fe.replicas) == 2
        clock.advance(11.0)
        assert ctl.evaluate()["action"] == "scale_down"
        assert [r.name for r in fe.replicas] == ["r0"]
        clock.advance(11.0)
        assert ctl.evaluate()["action"] is None   # at min_replicas
        kinds = [e["action"] for e in events]
        assert kinds == ["scale_up", "scale_up", "scale_down", "scale_down"]
        assert ctl.status()["transitions"][-1]["action"] == "scale_down"
    finally:
        if fe is not None:
            fe.stop()
        launcher.close()


def test_autoscaler_heals_sole_dead_replica(tmp_path, clock):
    """A preempted ONLY replica is still healable: the controller spawns
    the replacement before removing the corpse (the pool may never go
    empty), and traffic recovers."""
    from deeplearning4j_tpu.serving import FleetFrontend
    from deeplearning4j_tpu.util.http import post_json
    _write_zip(tmp_path / "v1.zip")
    launcher = InProcessLauncher(
        scan_dir=str(tmp_path), max_replicas=2,
        server_opts=dict(alert_interval_s=0),
        deploy_event={"kind": "deploy", "version": "v1"})
    fe = None
    try:
        url0 = launcher.launch("r0")
        fe = FleetFrontend([url0], names=["r0"], health_interval_s=1e9,
                           alert_interval_s=0).start()
        policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                                 cooldown_s=0.0, down_grace_s=0.0)
        ctl = AutoscaleController(fe, launcher, policy, interval_s=0)
        ctl.evaluate()
        launcher.kill("r0")                  # the whole pool dies
        r = ctl.evaluate()
        assert r["action"] == "replace_dead"
        [handle] = fe.replicas
        assert handle.name != "r0"
        res = post_json(fe.url + "/predict", {"data": [[0.1] * 6]},
                        timeout=30)
        assert res["version"] == "v1" and res["replica"] == handle.name
    finally:
        if fe is not None:
            fe.stop()
        launcher.close()


# ---------------------------------------------------------------- loadgen

def test_loadgen_open_loop_report():
    from tools.loadgen import predict_body, run_loadgen
    from deeplearning4j_tpu.serving import ServingServer
    from tools.smoke_telemetry import _tiny_net
    server = ServingServer(_tiny_net(), version="v1",
                           alert_interval_s=0).start()
    try:
        rep = run_loadgen(server.url, predict_body(nin=6), rate=150.0,
                          duration_s=0.5, seed=7, max_inflight=64)
        assert rep["arrivals"] > 30
        # every arrival is accounted for: completed with some status, or
        # dropped at the in-flight cap and COUNTED (open-loop honesty)
        assert rep["ok"] + rep["shed"] + rep["errors_5xx"] \
            + rep["transport_errors"] + rep["other_4xx"] \
            + rep["dropped_inflight"] == rep["arrivals"]
        assert rep["ok"] > 0 and rep["errors_5xx"] == 0
        assert rep["p99_ms"] >= rep["p50_ms"] > 0.0
        assert rep["offered_rate"] == 150.0 and rep["achieved_rate"] > 0
        # the arrival schedule is the seeded Poisson process: same seed,
        # same offered schedule (open loop = deterministic arrivals)
        import random
        r1 = random.Random(7)
        first_gap = r1.expovariate(150.0)
        assert 0 < first_gap < 1.0
    finally:
        server.stop()


# ------------------------------------------------------------------- smoke

def test_smoke_elastic_tool(tmp_path):
    """The full ManualClock autoscale arc (tools/smoke_elastic.py): ramp ->
    1->2->3 -> preempt -> failover (zero client 5xx) -> reap -> drain back
    to 1, transitions on /fleet/* and trace-correlated logs."""
    import tools.smoke_elastic as smoke
    out = smoke.run(scan_dir=str(tmp_path))
    assert out["client_5xx"] == 0
    assert out["pool_sizes"][0] == 1 and max(out["pool_sizes"]) == 3
    assert out["pool_sizes"][-1] == 1
    assert out["scale_ups"] == ["scale_up", "scale_up"]
    assert out["reap_action"] == "replace_dead"
    assert out["ramp_shed"] > 0 and out["failover_ok"] > 0
    assert out["fleet_sees_autoscale"] and out["scale_logs_traced"]
    assert out["preemptions"] == {"preempt-as1": 1}


@pytest.mark.slow
def test_subprocess_launcher_real_process_replica(tmp_path):
    """SubprocessLauncher: one OS process per replica — launch, warm
    deploy over HTTP, serve, terminate. Slow (a full Python+jax boot per
    replica); the in-process launcher covers the fast path in tier-1."""
    from deeplearning4j_tpu.elastic import SubprocessLauncher
    from deeplearning4j_tpu.util.http import get_json, post_json
    _write_zip(tmp_path / "v1.zip")
    launcher = SubprocessLauncher(
        str(tmp_path), max_replicas=1,
        server_opts=dict(alert_interval_s=0),
        deploy_event={"kind": "deploy", "version": "v1"})
    try:
        url = launcher.launch("p0")
        assert launcher.alive("p0")
        assert get_json(url + "/models", timeout=30)["active"] == "v1"
        res = post_json(url + "/predict", {"data": [[0.1] * 6]}, timeout=60)
        assert res["version"] == "v1"
        with pytest.raises(RuntimeError):
            launcher.launch("p1")            # max_replicas wall
    finally:
        launcher.close()
    assert not launcher.alive("p0")
