"""Checkpoint-format regression gate: a model zip committed by an earlier
build must keep loading with identical predictions (reference pattern:
deeplearning4j-core regressiontest/RegressionTest050.java — zips from old
releases pin configuration.json/coefficients.bin/updaterState.bin).

If this test breaks, the serialization format changed incompatibly: add a
back-compat loader path, do NOT regenerate the fixture."""
import os

import numpy as np

from deeplearning4j_tpu.util.model_serializer import ModelSerializer, ModelGuesser

FIX = os.path.join(os.path.dirname(__file__), "fixtures")


def test_pinned_model_zip_loads_and_predicts():
    net = ModelSerializer.restore(os.path.join(FIX, "regression_r3_mln.zip"))
    exp = np.load(os.path.join(FIX, "regression_r3_expected.npz"))
    # parameters identical to the committing build
    np.testing.assert_allclose(net.get_flat_params()[:32], exp["flat_head"],
                               rtol=0, atol=0)
    # predictions identical (conv/pool/BN/dense/softmax inference path)
    np.testing.assert_allclose(np.asarray(net.output(exp["x"])), exp["pred"],
                               rtol=1e-5, atol=1e-6)
    # updater state restored (Adam moments non-trivial after 3 steps)
    import jax
    moments = [l for l in jax.tree_util.tree_leaves(net.opt_state)
               if hasattr(l, "shape") and l.size > 1]
    assert any(float(np.abs(np.asarray(m)).max()) > 0 for m in moments), \
        "updaterState did not restore"
    # ModelGuesser sniffs the type from the zip alone
    g = ModelGuesser.load_model_guess(os.path.join(FIX, "regression_r3_mln.zip"))
    assert type(g).__name__ == "MultiLayerNetwork"
