"""Sharded tensor-store checkpoint tests (orbax; SURVEY.md §7 'sharded
tensor-store format' — params checkpoint without host gathering and restore
onto a mesh, including resharding-on-restore)."""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet, Adam)
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)
from deeplearning4j_tpu.util.sharded_checkpoint import (save_sharded,
                                                        restore_sharded)


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(n=32, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return X, Y


def test_sharded_checkpoint_roundtrip(tmp_path):
    net = _net()
    X, Y = _toy()
    for _ in range(3):
        net.fit(DataSet(X, Y))
    save_sharded(net, tmp_path / "ckpt")
    net2 = restore_sharded(tmp_path / "ckpt")
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(),
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(net.output(X)),
                               np.asarray(net2.output(X)), rtol=1e-6)
    # training continues with restored Adam moments: one more step matches
    net.fit(DataSet(X, Y))
    net2.fit(DataSet(X, Y))
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(),
                               rtol=1e-6, atol=1e-7)


def test_sharded_checkpoint_of_tp_model_and_reshard_restore(tmp_path):
    """Save a TP-sharded model (no host gather) and restore DIRECTLY onto
    mesh shardings."""
    from jax.sharding import PartitionSpec as P, NamedSharding
    net = _net(seed=5)
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    flat_before = net.get_flat_params()
    save_sharded(net, tmp_path / "tp_ckpt")

    # restore with explicit shardings matching the trainer's rules
    from deeplearning4j_tpu.parallel.sharding import param_shardings
    tmpl = _net(seed=5)
    pshard = param_shardings(tmpl.params, mesh, rules)
    net2 = restore_sharded(tmp_path / "tp_ckpt", shardings=pshard)
    np.testing.assert_allclose(net2.get_flat_params(), flat_before,
                               rtol=0, atol=0)
    # restored params are ALREADY mesh-sharded as requested
    w = net2.params["0"]["W"]
    assert w.sharding.spec == P(None, "model"), w.sharding


def test_default_restore_rederives_saved_sharding(tmp_path):
    """No `shardings` argument needed: the layout persisted at save time is
    re-derived for the current topology, so orbax always receives concrete
    shardings (no 'unsafe on a different topology' default path;
    VERDICT r3 #8). Any orbax warning escalates to an error here."""
    import warnings
    from jax.sharding import PartitionSpec as P
    net = _net(seed=5)
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    flat_before = net.get_flat_params()
    save_sharded(net, tmp_path / "ckpt")

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        net2 = restore_sharded(tmp_path / "ckpt")
    np.testing.assert_allclose(net2.get_flat_params(), flat_before,
                               rtol=0, atol=0)
    w = net2.params["0"]["W"]
    assert w.sharding.spec == P(None, "model"), w.sharding
    got = dict(zip(w.sharding.mesh.axis_names, w.sharding.mesh.devices.shape))
    assert got["data"] == 2 and got["model"] == 4, got


def test_default_restore_onto_differently_shaped_mesh(tmp_path):
    """Checkpoint written from a 4-device (2x2) mesh restores onto the
    8-device test topology with no explicit shardings: the data axis is
    rescaled (2x2 -> 4x2) and the persisted model-axis spec still applies."""
    import warnings
    from jax.sharding import PartitionSpec as P
    net = _net(seed=9)
    mesh = make_mesh(n_data=2, n_model=2, devices=jax.devices()[:4])
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    flat_before = net.get_flat_params()
    save_sharded(net, tmp_path / "ckpt")

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        net2 = restore_sharded(tmp_path / "ckpt")
    np.testing.assert_allclose(net2.get_flat_params(), flat_before,
                               rtol=0, atol=0)
    w = net2.params["0"]["W"]
    assert w.sharding.spec == P(None, "model")
    got = dict(zip(w.sharding.mesh.axis_names, w.sharding.mesh.devices.shape))
    assert got["data"] == 4 and got["model"] == 2, got
    # and training can continue on the re-derived layout
    net2.fit(DataSet(X, Y))


def test_default_restore_falls_back_when_rescaled_axis_stops_dividing(tmp_path):
    """A 4-device checkpoint with a dim-6 leaf sharded over the data axis
    cannot keep that spec when the axis rescales 2 -> 4 (6 % 4 != 0): the
    default restore must degrade to a replicated layout, not crash."""
    import warnings
    from jax.sharding import PartitionSpec as P
    conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    mesh = make_mesh(n_data=2, n_model=2, devices=jax.devices()[:4])
    rules = ShardingRules()
    rules.add(r"^0/b$", P("data"))  # dim 6 over data axis (2 divides, 4 won't)
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    flat_before = net.get_flat_params()
    save_sharded(net, tmp_path / "ckpt")

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        net2 = restore_sharded(tmp_path / "ckpt")  # 8 devices now
    np.testing.assert_allclose(net2.get_flat_params(), flat_before,
                               rtol=0, atol=0)
    b = net2.params["0"]["b"]
    assert b.sharding.spec == P(), b.sharding  # replicated fallback
