"""Sharded tensor-store checkpoint tests (orbax; SURVEY.md §7 'sharded
tensor-store format' — params checkpoint without host gathering and restore
onto a mesh, including resharding-on-restore)."""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet, Adam)
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)
from deeplearning4j_tpu.util.sharded_checkpoint import (save_sharded,
                                                        restore_sharded)


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(n=32, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return X, Y


def test_sharded_checkpoint_roundtrip(tmp_path):
    net = _net()
    X, Y = _toy()
    for _ in range(3):
        net.fit(DataSet(X, Y))
    save_sharded(net, tmp_path / "ckpt")
    net2 = restore_sharded(tmp_path / "ckpt")
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(),
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(net.output(X)),
                               np.asarray(net2.output(X)), rtol=1e-6)
    # training continues with restored Adam moments: one more step matches
    net.fit(DataSet(X, Y))
    net2.fit(DataSet(X, Y))
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(),
                               rtol=1e-6, atol=1e-7)


def test_sharded_checkpoint_of_tp_model_and_reshard_restore(tmp_path):
    """Save a TP-sharded model (no host gather) and restore DIRECTLY onto
    mesh shardings."""
    from jax.sharding import PartitionSpec as P, NamedSharding
    net = _net(seed=5)
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/W$", P(None, "model"))
    rules.add(r"^0/b$", P("model"))
    trainer = ShardedTrainer(net, mesh=mesh, rules=rules)
    X, Y = _toy(n=32)
    trainer.fit_batch(DataSet(X, Y))
    flat_before = net.get_flat_params()
    save_sharded(net, tmp_path / "tp_ckpt")

    # restore with explicit shardings matching the trainer's rules
    from deeplearning4j_tpu.parallel.sharding import param_shardings
    tmpl = _net(seed=5)
    pshard = param_shardings(tmpl.params, mesh, rules)
    net2 = restore_sharded(tmp_path / "tp_ckpt", shardings=pshard)
    np.testing.assert_allclose(net2.get_flat_params(), flat_before,
                               rtol=0, atol=0)
    # restored params are ALREADY mesh-sharded as requested
    w = net2.params["0"]["W"]
    assert w.sharding.spec == P(None, "model"), w.sharding
