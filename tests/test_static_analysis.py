"""graftlint tests: every rule (GL001–GL006) detects a seeded violation at
the right file:line, suppression comments AND baseline entries silence it,
the baseline round-trips through --baseline-update, and the whole-repo gate
(package + tools/) runs clean under the committed baseline inside tier-1."""
import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from deeplearning4j_tpu.analysis import Analyzer, Baseline, all_rules, get_rule

REPO = Path(__file__).resolve().parents[1]
BASELINE_PATH = REPO / "tools" / "lint_baseline.json"


def lint(src, rel_path="deeplearning4j_tpu/pkg/mod.py", rules=None):
    analyzer = Analyzer(rules=[get_rule(r) for r in rules] if rules else None,
                        root=str(REPO))
    violations, err = analyzer.analyze_source(src, rel_path)
    assert err is None, err
    return violations


# one (source, expected rule, expected flagged lines) seed per rule
SEEDS = {
    "GL001": ("""\
import time

def poll_deadline(timeout):
    return time.monotonic() + timeout

def stamp():
    return int(time.time() * 1000)
""", [4, 7]),
    "GL002": ("""\
import json

def overview(query, body):
    return 200, "application/json", json.dumps({"scores": []}).encode()
""", [4]),
    "GL003": ("""\
import threading

class Counter:
    def __init__(self):
        self._value = 0   # guarded by: self._lock
        self._lock = threading.Lock()

    def ok(self):
        with self._lock:
            self._value += 1
            return self._value

    def racy(self):
        return self._value + 1
""", [14]),
    "GL004": ("""\
import jax

@jax.jit
def step(params, x):
    return float(x.sum())
""", [5]),
    "GL005": ("""\
import threading

def start(work):
    t = threading.Thread(target=work)
    t.start()
    return t
""", [4]),
    "GL006": ("""\
import jax

def serve(requests, fn):
    for r in requests:
        out = jax.jit(fn)(r)
    return out
""", [5]),
    "GL007": ("""\
import numpy as np

def worker_loop(chunk):
    return np.asarray(chunk, np.float32)
""", [4]),
    "GL009": ("""\
import time

def fetch(url, tries):
    for attempt in range(tries):
        try:
            return url
        except OSError:
            time.sleep(attempt + 1.0)
""", [8]),
    "GL011": ("""\
import jax.numpy as jnp

def greedy_decode(model, params, ids, steps):
    toks = jnp.asarray(ids)
    for _ in range(steps):
        logits = model(params, toks)
        toks = jnp.concatenate([toks, logits[-1].argmax()[None]])
        pad = jnp.zeros((len(toks),))
    return toks
""", [7, 8]),
    "GL012": ("""\
import threading

class Pool:
    def refill(self):
        while self.need_more():
            t = threading.Thread(target=self.work, daemon=True)
            t.start()
""", [6]),
    "GL013": ("""\
import os

def publish(tmp, final):
    os.replace(tmp, final)
""", [4]),
    "GL014": ("""\
import numpy as np

def report_moments(state):
    mu = state["mu"].astype(np.float32)
    return mu
""", [4]),
}


@pytest.mark.parametrize("rule_id", sorted(SEEDS))
def test_rule_detects_seeded_violation_at_line(rule_id):
    src, lines = SEEDS[rule_id]
    violations = lint(src)
    flagged = [v for v in violations if v.rule == rule_id]
    assert [v.line for v in flagged] == lines, violations
    assert all(v.path.endswith("pkg/mod.py") for v in flagged)
    # no OTHER rule fires on the seed (rules stay orthogonal)
    assert [v.rule for v in violations] == [rule_id] * len(lines)


@pytest.mark.parametrize("rule_id", sorted(SEEDS))
def test_inline_suppression_comment_silences(rule_id):
    src, lines = SEEDS[rule_id]
    out = []
    for i, text in enumerate(src.splitlines(), 1):
        out.append(text + f"  # graftlint: disable={rule_id} <rationale>"
                   if i in lines else text)
    assert lint("\n".join(out) + "\n") == []


@pytest.mark.parametrize("rule_id", sorted(SEEDS))
def test_file_suppression_comment_silences(rule_id):
    src, _ = SEEDS[rule_id]
    assert lint(f"# graftlint: disable-file={rule_id}\n" + src) == []


@pytest.mark.parametrize("rule_id", sorted(SEEDS))
def test_baseline_entry_silences(rule_id):
    src, lines = SEEDS[rule_id]
    violations = lint(src)
    baseline = Baseline.from_violations(violations)
    new, matched = baseline.split(violations)
    assert new == [] and len(matched) == len(lines)
    # matching is a MULTISET: N entries absorb at most N identical findings,
    # so duplicating the violating code leaves the copies as new
    doubled = lint(src + "\n" + src.replace("def ", "def dup_"))
    extra_new, extra_matched = Baseline.from_violations(violations).split(doubled)
    assert len(extra_matched) == len(lines) and len(extra_new) == len(lines)


def test_standalone_suppression_comment_applies_to_next_line():
    src = ("import time\n"
           "# graftlint: disable=GL001 (benchmark of the raw clock itself)\n"
           "T = time.monotonic()\n")
    assert lint(src) == []


def test_bare_disable_suppresses_every_rule():
    src, _ = SEEDS["GL001"]
    marked = src.replace("    return time.monotonic() + timeout",
                         "    return time.monotonic() + timeout  "
                         "# graftlint: disable")
    assert [v.line for v in lint(marked)] == [7]


def test_suppression_marker_inside_string_is_ignored():
    src = ('import time\n'
           'S = "# graftlint: disable-file=GL001"\n'
           'T = time.time()\n')
    assert [v.rule for v in lint(src)] == ["GL001"]


# ---------------------------------------------------------------- per-rule
# edge semantics beyond the shared seed matrix

def test_gl001_allows_time_source_module_and_resolves_aliases():
    src, _ = SEEDS["GL001"]
    assert lint(src, rel_path="deeplearning4j_tpu/util/time_source.py") == []
    aliased = "import time as _t\nx = _t.monotonic()\n"
    assert [v.rule for v in lint(aliased)] == ["GL001"]
    assert lint("import time\ntime.sleep(0.1)\n") == []   # sleep is fine


def test_gl002_payload_module_and_dataflow_triggers():
    # every dumps in a payload module is payload serialization
    src = "import json\n\ndef to_json(d):\n    return json.dumps(d)\n"
    assert [v.line for v in lint(src, rel_path="deeplearning4j_tpu/ui/stats.py")] \
        == [4]
    assert lint(src) == []   # same code elsewhere: no HTTP evidence, quiet
    # dumps flowing into an HTTP request body through an assignment (the
    # raw urllib client itself now also trips GL008)
    flow = ("import json\n"
            "import urllib.request\n\n"
            "def post(url, d):\n"
            "    body = json.dumps(d).encode()\n"
            "    return urllib.request.Request(url, data=body)\n")
    assert [(v.rule, v.line) for v in lint(flow)] == [("GL002", 5),
                                                      ("GL008", 6)]
    # dumps written straight to a handler's wfile
    wf = ("import json\n\n"
          "class H:\n"
          "    def do_GET(self):\n"
          "        self.wfile.write(json.dumps({'a': 1}).encode())\n")
    assert [(v.rule, v.line) for v in lint(wf)] == [("GL002", 5)]


def test_gl002_allowlist_covers_util_http_only():
    """Satellite: telemetry/log/alert handlers must keep using dumps_safe —
    the ONLY module allowed raw json.dumps on a payload path is the strict
    serializer itself."""
    from deeplearning4j_tpu.analysis.rules import UnsafeJsonRule
    assert UnsafeJsonRule.ALLOW == ("util/http.py",)
    src, _ = SEEDS["GL002"]
    assert lint(src, rel_path="deeplearning4j_tpu/util/http.py") == []


def test_gl002_telemetry_ui_serving_endpoints_are_clean():
    """Satellite: no telemetry/serving/ui endpoint regresses to raw dumps."""
    report = Analyzer(rules=[get_rule("GL002")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu/telemetry", "deeplearning4j_tpu/serving",
         "deeplearning4j_tpu/ui", "deeplearning4j_tpu/util"])
    assert report.violations == [] and report.errors == []


def test_gl003_lock_guard_semantics():
    src, _ = SEEDS["GL003"]
    vs = lint(src)
    assert "self._value is guarded by self._lock" in vs[0].message
    # __init__ writes are exempt (no concurrent callers during construction);
    # a second guarded attribute under a DIFFERENT lock is tracked separately
    two_locks = ("""\
import threading

class T:
    def __init__(self):
        self._a = 0       # guarded by: self._la
        self._b = 0       # guarded by: self._lb
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def cross(self):
        with self._la:
            self._b += 1
""")
    vs = lint(two_locks)
    assert [(v.rule, v.line) for v in vs] == [("GL003", 12)]
    assert "self._lb" in vs[0].message


def test_gl004_partial_jit_and_wrapped_by_name():
    partial_form = ("""\
import functools
import jax
import numpy as np

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return np.asarray(x)

def build(g):
    def inner(x):
        return x.item()
    return jax.jit(inner)
""")
    vs = lint(partial_form)
    assert [(v.rule, v.line) for v in vs] == [("GL004", 7), ("GL004", 11)]
    # the same host-sync calls OUTSIDE jit are fine
    assert lint("import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n") == []


def test_gl005_daemon_or_joined_threads_pass():
    ok = ("""\
import threading

class W:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def close(self):
        self._t.join()
""")
    assert lint(ok) == []
    assert lint("import threading\n\n"
                "def s(w):\n"
                "    t = threading.Thread(target=w, daemon=True)\n"
                "    t.start()\n") == []
    swallow = ("""\
def worker(q):
    while True:
        try:
            q.step()
        except Exception:
            pass
""")
    assert [(v.rule, v.line) for v in lint(swallow)] == [("GL005", 5)]
    # a SPECIFIC exception pass is deliberate control flow, not a swallow
    assert lint(swallow.replace("except Exception:", "except KeyError:")) == []


def test_gl006_cached_handle_idiom_passes():
    cached = ("""\
import jax

def serve(requests, fn, cache):
    for r in requests:
        if "k" not in cache:
            cache["k"] = jax.jit(fn)
        out = cache["k"](r)
    return out
""")
    assert lint(cached) == []
    # a def boundary stops the loop ancestry (defining a fn in a loop body
    # doesn't invoke jit per iteration)
    deferred = ("""\
import jax

def build(fns):
    out = []
    for f in fns:
        def make(f=f):
            return jax.jit(f)
        out.append(make)
    return out
""")
    assert lint(deferred) == []


def test_gl007_scopes_and_dtype_forms():
    # every widening form fires inside the ETL hot modules, whatever the
    # function is called
    hot = ("""\
import numpy as np

def assemble(cols):
    a = np.asarray(cols, np.float32)
    b = np.array(cols, dtype=np.float64)
    c = a.astype(np.float32)
    d = a.astype("float64")
    e = a.astype(dtype=np.float32)
    return a, b, c, d, e
""")
    vs = lint(hot, rel_path="deeplearning4j_tpu/etl/pipeline.py")
    assert [(v.rule, v.line) for v in vs] == [("GL007", n)
                                             for n in (4, 5, 6, 7, 8)]
    # outside the hot modules only worker-loop-named functions are in scope
    assert lint(hot) == []
    loop = hot.replace("def assemble", "def _read_loop")
    assert [v.rule for v in lint(loop)] == ["GL007"] * 5
    # narrow/unchanged casts are not widening: no dtype, narrow targets,
    # module-level constants
    quiet = ("""\
import numpy as np

SCALE = np.asarray([1.0], np.float32)

def worker(chunk, dt):
    a = np.asarray(chunk)
    b = np.asarray(chunk, np.uint8)
    c = a.astype(np.int32)
    d = np.asarray(chunk, dt)
    return a, b, c, d
""")
    assert lint(quiet) == []


def test_gl007_prefetcher_put_path_is_narrow():
    """Satellite gate: the DevicePrefetcher transfer path must never regress
    to widening on the host — the exact anti-pattern this rule encodes."""
    report = Analyzer(rules=[get_rule("GL007")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu/etl/prefetch.py"])
    assert report.violations == [] and report.errors == []


def test_gl008_raw_http_client_forms_and_allowlist():
    # every urllib.request / http.client call form fires, plain or aliased
    seeded = ("""\
import urllib.request
import http.client
from urllib.request import urlopen as uo

def fetch(url):
    req = urllib.request.Request(url)
    with urllib.request.urlopen(req, timeout=5) as r:
        a = r.read()
    b = uo(url).read()
    conn = http.client.HTTPConnection("h")
    return a, b, conn
""")
    vs = lint(seeded, rules=["GL008"])
    assert [(v.rule, v.line) for v in vs] == [("GL008", n)
                                             for n in (6, 7, 9, 10)]
    # util/http.py is the one allowlisted module (the choke point itself)
    assert lint(seeded, rel_path="deeplearning4j_tpu/util/http.py",
                rules=["GL008"]) == []
    # non-socket urllib members stay quiet: parse helpers, error types,
    # and unresolvable local names
    quiet = ("""\
from urllib.parse import urlparse
import urllib.error

def ok(url, client):
    u = urlparse(url)
    try:
        return client.urlopen(url)
    except urllib.error.HTTPError as e:
        return e.code
""")
    assert lint(quiet, rules=["GL008"]) == []


def test_gl008_repo_choke_point_holds():
    """Satellite gate: outbound HTTP in the package goes through
    util.http.post_json/get_json — the propagation choke point. The single
    deliberate remainder (dataset artifact download) is baselined with a
    note; nothing else may join it silently."""
    report = Analyzer(rules=[get_rule("GL008")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    new, matched = Baseline.load(str(BASELINE_PATH)).split(report.violations)
    assert new == []
    assert [v.path for v in matched] == \
        ["deeplearning4j_tpu/datasets/fetchers/download.py"]


def test_gl009_retry_tell_vs_pacing_and_allowlist():
    # while-form fires too; the except handler is what makes it a retry
    retry_while = ("""\
import time

def deliver(msg):
    while True:
        try:
            return send(msg)
        except ConnectionError:
            time.sleep(0.5)
""")
    assert [(v.rule, v.line) for v in lint(retry_while, rules=["GL009"])] \
        == [("GL009", 8)]
    # a sleep that merely paces a loop (no except handler) is not a retry
    pacing = ("""\
import time

def watch(stop):
    while not stop.is_set():
        time.sleep(0.25)
""")
    assert lint(pacing, rules=["GL009"]) == []
    # a sleep in a nested def is that function's business, not the loop's
    nested = ("""\
import time

def build(jobs):
    for j in jobs:
        def backoff():
            try:
                return j()
            except OSError:
                time.sleep(1.0)
        yield backoff
""")
    assert lint(nested, rules=["GL009"]) == []
    # the mirror case: a PACING sleep in the loop body next to a callback
    # definition that catches its own errors — the handler belongs to the
    # nested scope, so the loop is not a retry loop
    pacing_with_cb = ("""\
import time

def schedule(jobs, submit):
    for j in jobs:
        def cb():
            try:
                return j()
            except OSError:
                pass
        submit(cb)
        time.sleep(0.25)
""")
    assert lint(pacing_with_cb, rules=["GL009"]) == []
    # a poller that catches an UNRELATED condition and paces outside the
    # handler is not retrying either: the sleep must live IN the handler
    poller = ("""\
import time
import queue

def drain(q, stop):
    while not stop.is_set():
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        time.sleep(0.1)
""")
    assert lint(poller, rules=["GL009"]) == []
    # the policy implementation itself is the one allowed home
    src, _ = SEEDS["GL009"]
    assert lint(src,
                rel_path="deeplearning4j_tpu/resilience/policy.py",
                rules=["GL009"]) == []


def test_gl009_repo_has_no_raw_retry_loops():
    """Satellite gate: every ad-hoc retry loop (broker reconnect, remote
    stats router, dataset download) was migrated to resilience.RetryPolicy;
    nothing may hand-roll a new one silently."""
    report = Analyzer(rules=[get_rule("GL009")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    new, matched = Baseline.load(str(BASELINE_PATH)).split(report.violations)
    assert new == [] and matched == []


def test_gl010_train_step_jits_donate_state():
    HOT = "deeplearning4j_tpu/nn/multilayer/network.py"
    # call form without donation over a params/opt_state-taking def fires
    seeded = ("""\
import jax

def make_step(tx):
    def train_step(params, opt_state, x):
        return params, opt_state
    return jax.jit(train_step)
""")
    assert [(v.rule, v.line) for v in lint(seeded, rel_path=HOT,
                                           rules=["GL010"])] \
        == [("GL010", 6)]
    # donate_argnums present -> quiet
    donated = seeded.replace("jax.jit(train_step)",
                             "jax.jit(train_step, donate_argnums=(0, 1))")
    assert lint(donated, rel_path=HOT, rules=["GL010"]) == []
    # decorator form fires (can't pass donate_argnums at all)
    deco = ("""\
import jax

@jax.jit
def pstep(params, opt_state, x):
    return params, opt_state
""")
    assert [(v.rule, v.line) for v in lint(deco, rel_path=HOT,
                                           rules=["GL010"])] \
        == [("GL010", 4)]
    # inline lambda with a state arg fires too
    lam = ("""\
import jax

def build():
    return jax.jit(lambda params, x: params)
""")
    assert [(v.rule, v.line) for v in lint(lam, rel_path=HOT,
                                           rules=["GL010"])] \
        == [("GL010", 4)]
    # a jit over a state-free function stays quiet (inference helpers that
    # don't touch params by name are not the rule's business)...
    quiet = ("""\
import jax

def make(fn):
    def fwd(xs, mask):
        return fn(xs, mask)
    return jax.jit(fwd)
""")
    assert lint(quiet, rel_path=HOT, rules=["GL010"]) == []
    # ...an opaque callee resolves to nothing and stays quiet...
    opaque = ("""\
import jax

def wrap(step_fn):
    return jax.jit(step_fn)
""")
    assert lint(opaque, rel_path=HOT, rules=["GL010"]) == []
    # ...and outside the nn//parallel/ hot modules the rule is scoped off
    assert lint(seeded, rel_path="deeplearning4j_tpu/serving/server.py",
                rules=["GL010"]) == []


def test_gl011_edges():
    # one-shot setup concatenation (no loop) in a decode-named fn is quiet
    setup = ("""\
import jax.numpy as jnp

def decode_setup(ids):
    return jnp.concatenate([jnp.asarray(ids), jnp.zeros((2,))])
""")
    assert lint(setup, rules=["GL011"]) == []
    # the same growing concat outside a decode-named function is quiet
    other = ("""\
import jax.numpy as jnp

def train_loop(xs):
    out = jnp.zeros((0,))
    for x in xs:
        out = jnp.concatenate([out, x])
    return out
""")
    assert lint(other, rules=["GL011"]) == []
    # python-list accumulation in a decode loop is the BLESSED host idiom
    host = ("""\
def generate(engine, cache, prompt, n):
    out = []
    for _ in range(n):
        cache, nxt = engine.step(cache, out[-1] if out else prompt[-1])
        out.append(int(nxt))
    return out
""")
    assert lint(host, rules=["GL011"]) == []
    # a loop inside a helper NESTED in a decode-named fn still counts
    nested = ("""\
import numpy as np

def generate_stream(model, ids, n):
    def run(toks):
        for _ in range(n):
            toks = np.concatenate([toks, model(toks)[-1:]])
        return toks
    return run(np.asarray(ids))
""")
    [v] = lint(nested, rules=["GL011"])
    assert v.rule == "GL011" and v.line == 6
    # len() sized shape ctor fires only inside the loop
    lenout = ("""\
import numpy as np

def beam_decode(model, ids, n):
    buf = np.zeros((len(ids) + n,))
    for i in range(n):
        buf[i] = model(buf)
    return buf
""")
    assert lint(lenout, rules=["GL011"]) == []


def test_gl012_edges():
    # a visible max-count guard in the spawning function is quiet
    guarded = ("""\
import threading

class Pool:
    def refill(self):
        while self.need_more():
            if len(self._workers) >= self.max_workers:
                break
            t = threading.Thread(target=self.work, daemon=True)
            t.start()
""")
    assert lint(guarded, rules=["GL012"]) == []
    # a non-blocking semaphore try-acquire is a bound too (loadgen idiom)
    sem = ("""\
import threading

def pump(jobs, inflight):
    while jobs:
        if not inflight.acquire(blocking=False):
            continue
        threading.Thread(target=jobs.pop, daemon=True).start()
""")
    assert lint(sem, rules=["GL012"]) == []
    # for-loop spawns are bounded by the iterable (_fan_out / worker pools)
    fan = ("""\
import threading

def fan_out(targets, fn):
    threads = [threading.Thread(target=fn, args=(t,), daemon=True)
               for t in targets]
    for t in threads:
        t.start()
""")
    assert lint(fan, rules=["GL012"]) == []
    # the launcher SPI module owns spawn (and its max_replicas wall)
    bare = ("""\
import threading

def respawn_loop(self):
    while True:
        threading.Thread(target=self.serve, daemon=True).start()
""")
    assert lint(bare, rel_path="deeplearning4j_tpu/elastic/launcher.py",
                rules=["GL012"]) == []
    # subprocess.Popen in an unguarded while loop fires like Thread
    popen = ("""\
import subprocess, sys

def keep_alive(cmd):
    while True:
        proc = subprocess.Popen([sys.executable] + cmd)
        proc.wait()
""")
    [v] = lint(popen, rules=["GL012"])
    assert v.rule == "GL012" and v.line == 5
    # an innermost def with its own guard is judged on its own body, even
    # defined inside someone else's unbounded loop
    nested = ("""\
import threading

def outer(self):
    while True:
        def spawn_some(n):
            while len(self._threads) < self.max_threads:
                threading.Thread(target=self.work, daemon=True).start()
        spawn_some(2)
""")
    assert lint(nested, rules=["GL012"]) == []


def test_gl012_repo_spawn_sites_are_bounded():
    """Satellite gate: the whole package + tools (the elastic subsystem,
    the loadgen, every worker pool) obeys the spawn bound — zero GL012
    findings, zero baselined remainders."""
    report = Analyzer(rules=[get_rule("GL012")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


def test_gl011_repo_decode_paths_are_clean():
    """Satellite gate: the decode subsystem itself (and everything else in
    the package + tools) obeys its own rule — zero GL011 findings, zero
    baselined remainders."""
    report = Analyzer(rules=[get_rule("GL011")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


def test_gl010_repo_hot_modules_donate_or_are_baselined():
    """Satellite gate: every params/opt_state jit in nn/ and parallel/
    donates its state args; the only remainders are the two inference
    executables (output() on both network classes), baselined with notes —
    nothing may join them silently."""
    report = Analyzer(rules=[get_rule("GL010")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu"])
    assert report.errors == []
    new, matched = Baseline.load(str(BASELINE_PATH)).split(report.violations)
    assert new == []
    assert sorted(v.path for v in matched) == \
        ["deeplearning4j_tpu/nn/graph/graph.py",
         "deeplearning4j_tpu/nn/multilayer/network.py"]


def test_gl013_edges():
    """util/fs.py (the one durable publisher) is allowed; os.rename and
    shutil.move are out of scope; aliased `from os import replace`
    resolves."""
    src = SEEDS["GL013"][0]
    assert lint(src, rel_path="deeplearning4j_tpu/util/fs.py") == []
    other = textwrap.dedent("""\
    import os
    import shutil

    def shuffle(a, b):
        os.rename(a, b)
        shutil.move(a, b)
    """)
    assert lint(other, rules=["GL013"]) == []
    aliased = textwrap.dedent("""\
    from os import replace

    def publish(tmp, final):
        replace(tmp, final)
    """)
    [v] = lint(aliased, rules=["GL013"])
    assert v.rule == "GL013" and v.line == 4


def test_gl013_repo_publishers_are_durable():
    """Satellite gate: every os.replace publisher in the package + tools
    goes through util.fs (checkpoint writer, ModelSerializer, blob store,
    baseline save, download cache) — zero GL013 findings, zero baselined
    remainders."""
    report = Analyzer(rules=[get_rule("GL013")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


def test_gl014_edges():
    """The designated quant modules (nn/quant.py, parallel/zero.py) are
    allowed; non-quant receivers, non-widening dtypes, and variable dtypes
    stay quiet; the ctor (`jnp.float32(qcodes)`), asarray-dtype=, and
    constant-subscript-key forms all fire."""
    src = SEEDS["GL014"][0]
    assert lint(src, rel_path="deeplearning4j_tpu/nn/quant.py") == []
    assert lint(src, rel_path="deeplearning4j_tpu/parallel/zero.py") == []
    quiet = textwrap.dedent("""\
    import numpy as np
    import jax.numpy as jnp

    def fine(x, qcodes, scales, in_dt, quantile, quantity):
        a = x.astype(np.float32)          # non-quant name
        b = qcodes.astype(jnp.bfloat16)   # narrowing, not f32/f64
        c = scales.astype(in_dt)          # variable dtype: unprovable
        d = quantile.astype(np.float32)   # 'quant' prefix != quant token
        e = quantity.astype(np.float32)
        return a, b, c, d, e
    """)
    assert lint(quiet, rules=["GL014"]) == []
    forms = textwrap.dedent("""\
    import numpy as np
    import jax.numpy as jnp

    def widen(state, qcodes, scales):
        a = jnp.float32(qcodes)
        b = np.asarray(scales, dtype=np.float64)
        c = state["qcodes"].astype("float32")
        return a, b, c
    """)
    flagged = lint(forms, rules=["GL014"])
    assert [v.line for v in flagged] == [5, 6, 7], flagged


def test_gl014_repo_gate_quant_stays_narrow():
    """Satellite gate: zero GL014 findings across the package + tools —
    every widening of quantized moment/weight leaves goes through the
    nn/quant codecs (or parallel/zero.py's canonical conversion)."""
    report = Analyzer(rules=[get_rule("GL014")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


HOT_SERVING = "deeplearning4j_tpu/serving/batcher.py"


def test_gl015_detects_bare_placement_in_hot_path():
    """A device_put with no sharding anywhere in its statement, and an
    implicit jnp placement inside a dispatch-named function with no
    sharding anywhere in the function, both fire in serving/."""
    seeded = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp

    def _dispatch(model, batch, mask):
        xb = jax.device_put(batch)
        yb = jax.device_put(mask, jax.devices()[0])
        zb = jnp.asarray(batch)
        return model.output(xb, yb, zb)
    """)
    flagged = lint(seeded, rel_path=HOT_SERVING, rules=["GL015"])
    assert [v.line for v in flagged] == [5, 6, 7], flagged
    assert all(v.rule == "GL015" for v in flagged)


def test_gl015_edges():
    # placement under a *_sharding helper (the mesh dispatch idiom) is quiet
    aware = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp

    def output(self, x):
        xb = jax.device_put(x, self.mesh_context.batch_sharding(x.ndim))
        return self.mesh_inner.output(xb)
    """)
    assert lint(aware, rel_path=HOT_SERVING, rules=["GL015"]) == []
    # sharding-awareness is judged per STATEMENT: a tree_map whose sibling
    # argument carries the shardings covers the lambda's bare device_put
    treemap = textwrap.dedent("""\
    import jax

    def init_cache(self):
        cache = self._cache_zeros()
        return jax.tree_util.tree_map(
            lambda leaf, s: jax.device_put(leaf, s), cache,
            self.cache_shardings())
    """)
    assert lint(treemap, rel_path="deeplearning4j_tpu/decode/engine.py",
                rules=["GL015"]) == []
    # implicit jnp placement outside a dispatch-named function is quiet
    cold = textwrap.dedent("""\
    import jax.numpy as jnp

    def summarize(rows):
        return jnp.asarray(rows).mean()
    """)
    assert lint(cold, rel_path=HOT_SERVING, rules=["GL015"]) == []
    # a dispatch-named fn that references a sharding ANYWHERE is judged
    # sharding-aware (the conversion feeds a later constrained placement)
    mixed = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp

    def prefill(self, ids):
        a = jnp.asarray(ids)
        return jax.device_put(a, self.mesh.cache_sharding(a.shape))
    """)
    assert lint(mixed, rel_path="deeplearning4j_tpu/decode/engine.py",
                rules=["GL015"]) == []
    # outside serving//decode/ the rule is scoped off entirely
    seeded = textwrap.dedent("""\
    import jax

    def dispatch(x):
        return jax.device_put(x)
    """)
    assert lint(seeded, rules=["GL015"]) == []
    assert lint(seeded, rel_path="deeplearning4j_tpu/etl/prefetch.py",
                rules=["GL015"]) == []


def test_gl015_repo_dispatch_paths_are_clean():
    """Satellite gate: the serving + decode subsystems obey their own rule
    — every batch/cache placement flows through a sharding, zero GL015
    findings, zero baselined remainders."""
    report = Analyzer(rules=[get_rule("GL015")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


HOT_DECODE = "deeplearning4j_tpu/decode/engine.py"


def test_gl016_detects_static_sampling_args():
    """Sampling params as jit static args fire in every resolvable
    spelling: static_argnames strings, static_argnums into a module-level
    def / inline lambda, and the @partial(jax.jit, ...) decorator."""
    seeded = textwrap.dedent("""\
    import functools

    import jax

    def _step(params, cache, ids, top_k):
        return ids

    by_name = jax.jit(_step, static_argnames=("temperature", "bucket"))
    by_num = jax.jit(_step, static_argnums=(3,))
    by_lambda = jax.jit(lambda ids, seed: ids, static_argnums=(1,))

    class Engine:
        @functools.partial(jax.jit, static_argnums=(2,))
        def step(self, ids, sampler):
            return ids
    """)
    flagged = lint(seeded, rel_path=HOT_DECODE, rules=["GL016"])
    assert [v.line for v in flagged] == [8, 9, 10, 13], flagged
    assert all(v.rule == "GL016" for v in flagged)
    assert "temperature" in flagged[0].message
    assert "top_k" in flagged[1].message
    assert "seed" in flagged[2].message
    assert "sampler" in flagged[3].message


def test_gl016_detects_sampling_cache_keys():
    """A sampling VALUE flowing into a lookup key fires: bare
    Name/Attribute keys, composite tuple keys, f-string keys, and the
    dict .get/.setdefault/.pop key argument."""
    seeded = textwrap.dedent("""\
    class Engine:
        def step(self, cfg, bucket, seed):
            fn = self._fns[(bucket, cfg.temperature)]
            fn = self._fns[f"step:{bucket}:{seed}"]
            fn = self._fns[cfg.seed]
            return self._cache.get((bucket, cfg.top_p))
    """)
    flagged = lint(seeded, rel_path=HOT_DECODE, rules=["GL016"])
    assert [v.line for v in flagged] == [3, 4, 5, 6], flagged
    assert all(v.rule == "GL016" for v in flagged)


def test_gl016_edges():
    # string-constant subscripts are the LEGITIMATE operand-dict /
    # request-parsing read — the field name is fixed, values live in the
    # array — and must stay quiet
    parsing = textwrap.dedent("""\
    def _handle_generate(self, d):
        t = d["temperature"]
        p = d.get("top_p", 1.0)
        ops["seed"][slot] = cfg.seed
        return t, p
    """)
    assert lint(parsing, rel_path="deeplearning4j_tpu/serving/server.py",
                rules=["GL016"]) == []
    # arithmetic index expressions are array math on a distribution, not
    # an executable-cache key (filter_probs_np's kth-largest threshold)
    math = textwrap.dedent("""\
    import numpy as np

    def filter_probs(p, config):
        order = np.argsort(-p)
        return p[order][config.top_k - 1]
    """)
    assert lint(math, rel_path="deeplearning4j_tpu/decode/sampling.py",
                rules=["GL016"]) == []
    # slicing a sampling-named ARRAY is operand math, not a key
    operand = textwrap.dedent("""\
    def keep_mask(probs, top_k, top_p):
        return probs * top_p[:, None] + top_k[:, None]
    """)
    assert lint(operand, rel_path="deeplearning4j_tpu/decode/sampling.py",
                rules=["GL016"]) == []
    # shape-bucket static args are the SANCTIONED jit-cache discipline
    shapes = textwrap.dedent("""\
    import jax
    fn = jax.jit(step_fn, static_argnames=("bucket", "window"))
    """)
    assert lint(shapes, rel_path=HOT_DECODE, rules=["GL016"]) == []
    # whole-word matching: `reseed`/`processed` don't contain a sampling
    # param, `seed_bucket` does
    words = textwrap.dedent("""\
    class E:
        def step(self, reseed, processed, seed_bucket):
            a = self._fns[(1, reseed)]
            b = self._fns[(1, processed)]
            return self._fns[(1, seed_bucket)]
    """)
    flagged = lint(words, rel_path=HOT_DECODE, rules=["GL016"])
    assert [v.line for v in flagged] == [5], flagged
    # outside serving//decode/ the rule is scoped off entirely (training
    # code may legitimately close over a fixed seed)
    cold = textwrap.dedent("""\
    import jax
    fn = jax.jit(step_fn, static_argnames=("temperature",))
    """)
    assert lint(cold, rules=["GL016"]) == []
    assert lint(cold, rel_path="deeplearning4j_tpu/zoo/lm.py",
                rules=["GL016"]) == []


def test_gl016_repo_decode_paths_are_clean():
    """Satellite gate: the decode + serving subsystems obey their own rule
    — sampling params ride as array operands everywhere, zero GL016
    findings, zero baselined remainders."""
    report = Analyzer(rules=[get_rule("GL016")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


HOT_NN = "deeplearning4j_tpu/nn/graph/graph.py"


def test_gl017_detects_bare_jit_cache_store():
    """A jax.jit result stored straight into a cache subscript or via
    dict.setdefault fires in the serving/decode/nn hot modules."""
    seeded = textwrap.dedent("""\
    import jax

    class Net:
        def _get_step(self, key, fn):
            self._jit_cache[key] = jax.jit(fn, donate_argnums=(0, 1))
            return self._jit_cache[key]

        def _get_fwd(self, key, fn):
            return self._fns.setdefault(key, jax.jit(fn))
    """)
    for rel in (HOT_NN, HOT_SERVING, HOT_DECODE):
        flagged = lint(seeded, rel_path=rel, rules=["GL017"])
        assert [v.line for v in flagged] == [5, 9], (rel, flagged)
        assert all(v.rule == "GL017" for v in flagged)


def test_gl017_edges():
    # the telemetry-routed store (the repo idiom) is quiet
    tracked = textwrap.dedent("""\
    import jax
    from ..telemetry.xla import timed_first_call

    class Net:
        def _get_step(self, key, fn):
            self._jit_cache[key] = timed_first_call(
                jax.jit(fn, donate_argnums=(0, 1)), f"train_step:{key}")
            return self._jit_cache[key]
    """)
    assert lint(tracked, rel_path=HOT_NN, rules=["GL017"]) == []
    # returning a fresh jit (factory methods) and binding a local name are
    # NOT cache stores — shallow-and-sound, the rule stays quiet
    quiet = textwrap.dedent("""\
    import jax

    class Net:
        def _make_step(self, fn):
            return jax.jit(fn, donate_argnums=(2,))

        def _once(self, fn):
            pstep = jax.jit(fn)
            return pstep(1.0)
    """)
    assert lint(quiet, rel_path=HOT_DECODE, rules=["GL017"]) == []
    # outside the hot prefixes the rule is scoped off entirely
    seeded = textwrap.dedent("""\
    import jax

    def cache_it(cache, key, fn):
        cache[key] = jax.jit(fn)
    """)
    assert lint(seeded, rules=["GL017"]) == []
    assert lint(seeded, rel_path="deeplearning4j_tpu/etl/prefetch.py",
                rules=["GL017"]) == []
    # an inline suppression with a rationale still silences it in-scope
    marked = seeded.replace("cache[key] = jax.jit(fn)",
                            "cache[key] = jax.jit(fn)  "
                            "# graftlint: disable=GL017 <deliberate>")
    assert lint(marked, rel_path=HOT_SERVING, rules=["GL017"]) == []


def test_gl017_repo_jit_caches_are_tracked():
    """Satellite gate: every executable cache in serving/, decode/, and nn/
    funnels through the compile-telemetry seam — zero GL017 findings, zero
    baselined remainders."""
    report = Analyzer(rules=[get_rule("GL017")], root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    assert report.errors == []
    assert report.violations == [], [str(v) for v in report.violations]


# ---------------------------------------------------------------- baseline

def test_baseline_round_trip_via_cli(tmp_path):
    """--baseline-update then a clean re-run exits 0; removing the baseline
    fails the gate again; notes survive the rewrite."""
    target = tmp_path / "mod.py"
    target.write_text(SEEDS["GL001"][0])
    bl = tmp_path / "bl.json"

    def run(*extra):
        return subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"), str(target),
             "--baseline", str(bl), *extra],
            capture_output=True, text=True, cwd=str(REPO))

    assert run().returncode == 1                      # dirty, no baseline
    assert run("--baseline-update").returncode == 0   # write baseline
    assert run().returncode == 0                      # now clean
    # a note added by a human survives the next --baseline-update
    data = json.loads(bl.read_text())
    data["entries"][0]["note"] = "kept: raw-clock benchmark"
    bl.write_text(json.dumps(data))
    assert run("--baseline-update").returncode == 0
    assert "kept: raw-clock benchmark" in bl.read_text()
    assert run("--no-baseline").returncode == 1       # baseline ignored


def test_baseline_matches_by_code_not_line():
    src, lines = SEEDS["GL001"]
    baseline = Baseline.from_violations(lint(src))
    drifted = "# a new comment shifting every line\n" + src
    new, matched = baseline.split(lint(drifted))
    assert new == [] and len(matched) == len(lines)


def test_stale_baseline_entries_are_detectable():
    src, _ = SEEDS["GL001"]
    baseline = Baseline.from_violations(lint(src))
    fixed = src.replace("time.monotonic() + timeout", "monotonic_s() + timeout")
    assert len(baseline.stale_entries(lint(fixed))) == 1


def test_scoped_baseline_update_preserves_out_of_scope_entries(tmp_path):
    """A --baseline-update restricted to one path (or rule subset) must not
    delete entries for files it never analyzed."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text(SEEDS["GL001"][0])
    b.write_text(SEEDS["GL005"][0])
    bl = tmp_path / "bl.json"

    def run(*argv):
        return subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--baseline", str(bl), *argv],
            capture_output=True, text=True, cwd=str(REPO))

    assert run(str(a), str(b), "--baseline-update").returncode == 0
    entries = json.loads(bl.read_text())["entries"]
    assert {e["rule"] for e in entries} == {"GL001", "GL005"}
    # scoped re-derive of a.py only: b.py's GL005 entry survives verbatim
    assert run(str(a), "--baseline-update").returncode == 0
    after = json.loads(bl.read_text())["entries"]
    assert {e["rule"] for e in after} == {"GL001", "GL005"}
    # rule-scoped update keeps the other rule's entries too
    assert run(str(a), str(b), "--rules", "GL005",
               "--baseline-update").returncode == 0
    assert {e["rule"] for e in json.loads(bl.read_text())["entries"]} == \
        {"GL001", "GL005"}
    assert run(str(a), str(b)).returncode == 0      # still clean overall


def test_baseline_update_refuses_on_parse_errors(tmp_path):
    """An unparseable file yields zero violations, so updating the baseline
    past it would silently delete that file's annotated entries — the update
    must refuse instead."""
    good = tmp_path / "good.py"
    good.write_text(SEEDS["GL001"][0])
    bl = tmp_path / "bl.json"

    def run(*argv):
        return subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--baseline", str(bl), *argv],
            capture_output=True, text=True, cwd=str(REPO))

    assert run(str(tmp_path), "--baseline-update").returncode == 0
    before = bl.read_text()
    (tmp_path / "broken.py").write_text("def broken(:\n")
    proc = run(str(tmp_path), "--baseline-update")
    assert proc.returncode == 1
    assert "baseline NOT updated" in proc.stdout
    assert bl.read_text() == before              # untouched


def test_nonexistent_path_fails_loudly(tmp_path):
    """A typoed path in CI must exit 1, not lint zero files green."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"),
         str(tmp_path / "no_such_dir")],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 1
    assert "does not exist" in proc.stdout


# ---------------------------------------------------------------- CLI + gate

def test_cli_json_format_is_machine_readable(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(SEEDS["GL002"][0])
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.analysis", str(target),
         "--no-baseline", "--format=json"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out["ok"] is False and out["files_checked"] == 1
    (v,) = out["new"]
    assert v["rule"] == "GL002" and v["line"] == 4 and v["code"]


def test_cli_rule_subset_and_list_rules():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--list-rules"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 0
    for rule in all_rules():
        assert rule.id in proc.stdout and rule.rationale
    assert [r.id for r in all_rules()] == \
        ["GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
         "GL008", "GL009", "GL010", "GL011", "GL012", "GL013", "GL014",
         "GL015", "GL016", "GL017", "GL018", "GL019", "GL020"]


def test_repo_gate_is_clean_and_fast():
    """THE gate: the whole package + tools/ lint clean under the committed
    baseline, in well under the 10s budget."""
    t0 = time.monotonic()
    report = Analyzer(root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    baseline = Baseline.load(str(BASELINE_PATH))
    new, _ = baseline.split(report.violations)
    elapsed = time.monotonic() - t0
    assert report.errors == []
    assert new == [], "NEW lint violations (fix, suppress with a rationale " \
        "comment, or tools/lint.py --baseline-update):\n" + \
        "\n".join(str(v) for v in new)
    assert report.files_checked > 100
    assert elapsed < 10.0, f"lint gate took {elapsed:.1f}s (budget 10s)"


def test_committed_baseline_is_note_complete_and_not_stale():
    """Policy: baselined leftovers must be annotated (why is it tolerated?),
    must never include GL001/GL002 (those are always fixed for real), and
    must not outlive the violation they excuse."""
    baseline = Baseline.load(str(BASELINE_PATH))
    for entry in baseline.entries:
        assert entry["note"].strip(), f"baseline entry without a note: {entry}"
        assert entry["rule"] not in ("GL001", "GL002"), \
            f"clock/json findings must be FIXED, not baselined: {entry}"
    report = Analyzer(root=str(REPO)).analyze_paths(
        ["deeplearning4j_tpu", "tools"])
    stale = baseline.stale_entries(report.violations)
    assert stale == [], f"stale baseline entries (already fixed): {stale}"
