"""Real-photo 32x32 fixture through the untouched CIFAR binary path
(VERDICT r4 next #7): tests/fixtures/cifar_real holds 960 train / 240 test
genuine photograph crops (8 texture/object classes from the environment's
bundled real photos; provenance in tools/make_cifar_fixture.py) in the exact
CIFAR-10 record layout the reference's CifarDataSetIterator.java consumes —
label byte + 3072 RGB plane bytes. The train/test split is spatial with a
32 px gap, so the accuracy gate can't be leakage.
"""
import gzip
import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers.standard import (
    CifarDataSetIterator, load_cifar, real32_gate_accuracy, _find_cifar_dir)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "cifar_real")


@pytest.fixture(autouse=True)
def pin_fixture_dir(monkeypatch):
    """Force the committed fixture even on machines with a full CIFAR-10
    copy in a higher-priority candidate dir (CIFAR_DIR wins the search, so
    pointing it at the fixture makes these tests deterministic — the
    mnist_real tests use the same trick)."""
    monkeypatch.setenv("CIFAR_DIR", FIXTURE)


def test_fixture_is_real_not_synthetic():
    d = _find_cifar_dir()
    assert d is not None, "cifar_real fixture not found"
    x, y, names = load_cifar(train=True)
    assert x.shape == (960, 32, 32, 3), (
        "real fixture not picked up — synthetic fallback engaged")
    assert names == ["sky", "building", "foliage", "water", "petal", "leaf",
                     "flag", "face"]
    # real photographs: channel means differ strongly per class (the
    # synthetic fallback's classes are near-identical gray noise)
    sky = x[y == 0].mean(axis=(0, 1, 2))
    leaf = x[y == 5].mean(axis=(0, 1, 2))
    assert sky.mean() > 0.75          # pale hazy sky
    assert leaf.mean() < 0.25         # dark blurred foliage
    assert sorted(np.unique(y)) == list(range(8))


def test_cifar_binary_layout_parses_like_reference():
    """The fixture bytes follow CifarDataSetIterator.java's record layout:
    byte 0 = label, bytes 1..3072 = R,G,B planes row-major — verified by
    re-parsing the raw gz independently of the fetcher."""
    with open(os.path.join(FIXTURE, "test_batch.bin.gz"), "rb") as f:
        raw = np.frombuffer(gzip.decompress(f.read()), np.uint8)
    assert len(raw) % 3073 == 0
    recs = raw.reshape(-1, 3073)
    assert recs.shape[0] == 240
    assert recs[:, 0].max() == 7
    x, y, _ = load_cifar(train=False)
    manual = recs[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(x, manual.astype(np.float32) / 255.0)
    np.testing.assert_array_equal(y, recs[:, 0])


def test_iterator_one_hots_to_ten_classes():
    it = CifarDataSetIterator(batch_size=64, train=True)
    ds = it.next()
    assert ds.features.shape == (64, 32, 32, 3)
    assert ds.labels.shape == (64, 10)       # CIFAR-10-shaped head
    assert it.labels[0] == "sky"


def test_convnet_gate_on_real_heldout():
    """The SHARED gate recipe (datasets/fetchers/standard.py — the same
    function bench.py publishes as real32_test_acc) must reach 82% held-out
    accuracy on the spatially-split real crops (measured 0.88-0.95 across
    seeds/platforms; the weak class is flag-vs-building — red stripes vs
    the red pagoda at 32 px)."""
    acc = real32_gate_accuracy(epochs=10)
    assert acc is not None, "fixture missing — gate meaningless"
    assert acc >= 0.82, f"held-out accuracy {acc:.3f} < 0.82 on real crops"
