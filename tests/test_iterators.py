"""Prefetch-overlap property tests (VERDICT r3 #3).

The reference's AsyncDataSetIterator exists to hide host-side data cost
behind device compute (AsyncDataSetIterator.java:38-76: prefetch thread +
bounded queue + device affinity). The testable form of that claim: with a
producer that takes `t_link` per batch and a consumer that takes `t_compute`
per batch, total wall for N batches must track
startup + N*max(t_link, t_compute), NOT N*(t_link + t_compute). bench.py
reports the same two legs measured on the real chip (e2e_link_ms /
e2e_wall_ms_per_batch); the hard assertion lives here where timing is
controllable.
"""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator.base import (
    AsyncDataSetIterator, DataSetIterator, DevicePrefetchIterator,
    ListDataSetIterator)


class SlowIterator(DataSetIterator):
    """Simulates an expensive host-side pipeline (decode/augment/link)."""

    def __init__(self, n_batches, delay_s, batch=8):
        self.n = n_batches
        self.delay = delay_s
        self._i = 0
        rng = np.random.default_rng(0)
        self._x = rng.random((batch, 4)).astype(np.float32)
        self._y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch)]

    def next(self):
        time.sleep(self.delay)
        self._i += 1
        return DataSet(self._x, self._y)

    def has_next(self):
        return self._i < self.n

    def reset(self):
        self._i = 0


@pytest.mark.parametrize("cls", [DevicePrefetchIterator, AsyncDataSetIterator])
def test_prefetch_overlaps_producer_with_consumer(cls):
    n, t_link, t_compute = 8, 0.05, 0.05
    serial = n * (t_link + t_compute)          # what NO overlap would cost
    pipelined = t_link + n * max(t_link, t_compute)  # ideal overlap

    it = cls(SlowIterator(n, t_link), queue_size=2)
    t0 = time.perf_counter()
    seen = 0
    while it.has_next():
        it.next()
        time.sleep(t_compute)                  # stand-in for device compute
        seen += 1
    wall = time.perf_counter() - t0
    assert seen == n
    # must beat serial by a clear margin and track the pipelined ideal
    # (generous slack: CI schedulers jitter sleeps)
    assert wall < 0.80 * serial, (
        f"wall {wall:.3f}s vs serial {serial:.3f}s — no overlap happened")
    assert wall < pipelined * 1.35


def test_prefetch_draining_and_reuse():
    """Queue drains fully and reset() restarts the producer thread."""
    it = DevicePrefetchIterator(SlowIterator(3, 0.0), queue_size=2)
    got = [it.next() for _ in range(3)]
    assert not it.has_next()
    assert all(g.features.shape == (8, 4) for g in got)
    it.reset()
    assert it.has_next()
    assert sum(1 for _ in it) == 3


def test_prefetch_propagates_producer_error():
    class Boom(SlowIterator):
        def next(self):
            if self._i == 1:
                raise RuntimeError("decode failed")
            return super().next()

    it = DevicePrefetchIterator(Boom(3, 0.0), queue_size=2)
    with pytest.raises(RuntimeError, match="decode failed"):
        # already-prefetched batches are delivered first; the error then
        # surfaces from has_next() (iteration protocol) rather than being lost
        for _ in it:
            pass


class _BoomOnce(SlowIterator):
    """Reader that fails mid-stream on the first pass only."""

    def __init__(self, n_batches, boom_at=1):
        super().__init__(n_batches, 0.0)
        self.boom_at = boom_at
        self._armed = True

    def next(self):
        if self._armed and self._i == self.boom_at:
            raise RuntimeError("decode failed")
        return super().next()

    def reset(self):
        super().reset()
        self._armed = False


@pytest.mark.parametrize("cls", [AsyncDataSetIterator, DevicePrefetchIterator])
def test_prefetch_error_surfaces_on_close_exactly_once(cls):
    """A worker error raised AFTER the consumer stops calling next() used to
    be swallowed; close() must re-raise it — and exactly once."""
    import time as _time
    it = cls(_BoomOnce(4), queue_size=4)
    it.next()                       # consume one batch, then stop pulling
    deadline = _time.monotonic() + 20
    while it._error is None and _time.monotonic() < deadline:
        _time.sleep(0.01)           # worker hits the failure in background
    with pytest.raises(RuntimeError, match="decode failed"):
        it.close()
    it.close()                      # second close: clean no-op
    assert not it.has_next()        # and no third surfacing from has_next


@pytest.mark.parametrize("cls", [AsyncDataSetIterator, DevicePrefetchIterator])
def test_prefetch_error_surfaces_on_reset_exactly_once(cls):
    """reset() after a mid-stream failure re-raises the pending error once,
    and the restarted pass (underlying reset cleared the fault) runs clean."""
    import time as _time
    it = cls(_BoomOnce(4), queue_size=4)
    it.next()
    deadline = _time.monotonic() + 20
    while it._error is None and _time.monotonic() < deadline:
        _time.sleep(0.01)
    with pytest.raises(RuntimeError, match="decode failed"):
        it.reset()
    # the raise happened AFTER the restart: the iterator is usable again
    assert sum(1 for _ in it) == 4
    it.close()


@pytest.mark.parametrize("cls", [AsyncDataSetIterator, DevicePrefetchIterator])
def test_prefetch_error_not_raised_twice_across_paths(cls):
    """The iteration path (has_next raise) claims the error; reset()/close()
    afterwards must NOT raise the same error again."""
    it = cls(_BoomOnce(4), queue_size=4)
    with pytest.raises(RuntimeError, match="decode failed"):
        for _ in it:
            pass
    it.reset()                      # no second raise; restarts cleanly
    assert sum(1 for _ in it) == 4
    it.close()
