"""Cloud module tests (reference: deeplearning4j-aws — S3 blob IO + EC2
provisioning; exercised hermetically through the local backends)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.cloud import (LocalBlobStore, BlobDataSetIterator,
                                      get_blob_store, ClusterSetup,
                                      HostProvisioner, LocalTransport)
from deeplearning4j_tpu.datasets.dataset import DataSet


def test_blob_store_roundtrip(tmp_path):
    store = LocalBlobStore(tmp_path / "bucket")
    store.upload_bytes(b"hello", "models/a.bin")
    store.upload_bytes(b"world", "models/b.bin")
    store.upload_bytes(b"x", "other/c.bin")
    assert store.download_bytes("models/a.bin") == b"hello"
    assert store.list_keys("models/") == ["models/a.bin", "models/b.bin"]
    local = tmp_path / "dl" / "a.bin"
    store.download("models/a.bin", local)
    assert open(local, "rb").read() == b"hello"
    store.delete("models/b.bin")
    assert store.list_keys("models/") == ["models/a.bin"]
    with pytest.raises(ValueError):
        store.download_bytes("../escape")


def test_get_blob_store_resolution(tmp_path):
    s = get_blob_store(f"file://{tmp_path}/b1")
    assert isinstance(s, LocalBlobStore)
    s2 = get_blob_store(str(tmp_path / "b2"))
    assert isinstance(s2, LocalBlobStore)
    with pytest.raises((ImportError, NotImplementedError)):
        get_blob_store("s3://bucket")


def test_blob_dataset_iterator_trains(tmp_path):
    """DataSets stored as blobs feed fit() (reference:
    BaseS3DataSetIterator)."""
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    MultiLayerNetwork, Sgd)
    store = LocalBlobStore(tmp_path / "ds")
    rng = np.random.default_rng(0)
    w = rng.normal(size=(6, 3))
    for i in range(4):
        X = rng.normal(size=(16, 6)).astype(np.float32)
        Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, 1)]
        BlobDataSetIterator.save_dataset(store, f"train/batch_{i}.npz",
                                         DataSet(X, Y))
    it = BlobDataSetIterator(store, "train/")
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=3)
    assert net.iteration_count == 12


def test_cluster_setup_local_transport(tmp_path):
    hosts = ["worker0", "worker1", "worker2"]
    # sandboxed per-host filesystems: concurrent uploads of the same logical
    # remote path must not collide
    cs = ClusterSetup(hosts, LocalTransport(sandbox_root=tmp_path / "hosts"))
    outs = cs.run_on_all("echo provisioned-$USER")
    assert set(outs) == set(hosts)
    assert all("provisioned" in o for o in outs.values())

    script = tmp_path / "setup.sh"
    script.write_text("echo bootstrap-ok\n")
    outs = cs.bootstrap(str(script), remote_path="/tmp/setup.sh")
    assert all("bootstrap-ok" in o for o in outs.values())
    for h in hosts:
        assert os.path.exists(tmp_path / "hosts" / h / "tmp" / "setup.sh")


def test_host_provisioner_retries():
    class Flaky(LocalTransport):
        def __init__(self):
            self.calls = 0

        def run(self, host, command, timeout=300):
            self.calls += 1
            if self.calls < 3:
                return 1, "", "transient"
            return super().run(host, command, timeout)

    t = Flaky()
    p = HostProvisioner(t, "h1", retries=3)
    out = p.run("echo ok")
    assert "ok" in out and t.calls == 3

    t2 = Flaky()
    p2 = HostProvisioner(t2, "h1", retries=2)  # not enough retries
    with pytest.raises(RuntimeError, match="rc=1"):
        p2.run("echo ok")


# -------------------------------------------- download + cache machinery

def test_download_file_retry_and_checksum(tmp_path):
    import hashlib
    from deeplearning4j_tpu.datasets.fetchers.download import download_file
    src = tmp_path / "payload.bin"
    src.write_bytes(b"A" * 1000)
    md5 = hashlib.md5(b"A" * 1000).hexdigest()
    url = src.as_uri()
    dest = tmp_path / "cache" / "payload.bin"
    assert download_file(url, dest, md5=md5) == str(dest)
    assert dest.read_bytes() == b"A" * 1000
    # cache hit: deleting the source must not matter
    src.unlink()
    assert download_file(url, dest, md5=md5) == str(dest)
    # checksum mismatch fails after bounded retries
    src2 = tmp_path / "other.bin"
    src2.write_bytes(b"B")
    with pytest.raises(IOError, match="after 2 tries"):
        download_file(src2.as_uri(), tmp_path / "cache" / "o.bin",
                      md5="0" * 32, max_tries=2, backoff_s=0)


def test_download_and_extract_tar(tmp_path):
    import tarfile
    from deeplearning4j_tpu.datasets.fetchers.download import download_and_extract
    inner = tmp_path / "data.txt"
    inner.write_text("mnist-like-content")
    tar = tmp_path / "dataset.tar.gz"
    with tarfile.open(tar, "w:gz") as t:
        t.add(inner, arcname="data.txt")
    out = download_and_extract(tar.as_uri(), cache_dir=str(tmp_path / "cache"))
    assert open(os.path.join(out, "data.txt")).read() == "mnist-like-content"
    # second call is a pure cache hit (archive source can disappear)
    tar.unlink()
    out2 = download_and_extract(tar.as_uri(), cache_dir=str(tmp_path / "cache"))
    assert out2 == out


def test_blob_store_prefix_sibling_escape_blocked(tmp_path):
    store = LocalBlobStore(tmp_path / "store")
    (tmp_path / "store2").mkdir()
    (tmp_path / "store2" / "secret").write_text("x")
    with pytest.raises(ValueError):
        store.download_bytes("../store2/secret")
