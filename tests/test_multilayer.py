"""MultiLayerNetwork behavior tests (reference:
deeplearning4j-core/src/test/java/org/deeplearning4j/nn/multilayer/
MultiLayerTest.java, BackPropMLPTest.java, TestSetGetParameters.java).
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, RnnOutputLayer, GravesLSTM,
                                ConvolutionLayer, SubsamplingLayer, DropoutLayer,
                                MultiLayerNetwork, DataSet, INDArrayDataSetIterator,
                                ListDataSetIterator, AsyncDataSetIterator,
                                Adam, Sgd, Nesterovs, WeightInit, BackpropType,
                                ModelSerializer, ScoreIterationListener,
                                CollectScoresIterationListener)


def _toy_classification(n=256, nin=4, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nin)).astype(np.float32)
    w = rng.normal(size=(nin, nout))
    y = np.argmax(X @ w + 0.1 * rng.normal(size=(n, nout)), axis=1)
    return X, np.eye(nout, dtype=np.float32)[y]


def _mlp_conf(nin=4, nout=3, updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(42).updater(updater or Adam(1e-2)).weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=nout, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(nin))
            .build())


def test_fit_reduces_score_and_learns():
    X, Y = _toy_classification()
    net = MultiLayerNetwork(_mlp_conf()).init()
    s0 = net.score(DataSet(X, Y))
    net.fit(INDArrayDataSetIterator(X, Y, 64), epochs=30)
    s1 = net.score(DataSet(X, Y))
    assert s1 < s0 * 0.5
    acc = net.evaluate(ListDataSetIterator([DataSet(X, Y)])).accuracy()
    assert acc > 0.9


def test_updaters_all_train():
    X, Y = _toy_classification(n=128)
    from deeplearning4j_tpu import AdaGrad, AdaDelta, RmsProp
    for upd in (Sgd(0.1), Nesterovs(0.05), Adam(1e-2), AdaGrad(learning_rate=0.1),
                AdaDelta(), RmsProp(learning_rate=1e-2)):
        net = MultiLayerNetwork(_mlp_conf(updater=upd)).init()
        s0 = net.score(DataSet(X, Y))
        net.fit(INDArrayDataSetIterator(X, Y, 64), epochs=10)
        assert net.score(DataSet(X, Y)) < s0, type(upd).__name__


def test_param_flat_view_roundtrip():
    """Flattened param view get/set (reference: TestSetGetParameters.java)."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    flat = net.get_flat_params()
    assert flat.size == net.num_params()
    flat2 = flat * 2.0
    net.set_flat_params(flat2)
    np.testing.assert_allclose(net.get_flat_params(), flat2, rtol=1e-6)


def test_model_serializer_roundtrip(tmp_path):
    """Checkpoint zip round-trip (reference: ModelSerializer + regression tests)."""
    X, Y = _toy_classification(n=64)
    net = MultiLayerNetwork(_mlp_conf()).init()
    net.fit(INDArrayDataSetIterator(X, Y, 32), epochs=3)
    path = str(tmp_path / "model.zip")
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(), rtol=1e-6)
    out1 = np.asarray(net.output(X[:8]))
    out2 = np.asarray(net2.output(X[:8]))
    np.testing.assert_allclose(out1, out2, rtol=1e-5)
    # updater state restored: one more identical fit step stays identical
    ds = DataSet(X[:32], Y[:32])
    net.fit_batch(ds)
    net2.fit_batch(ds)
    np.testing.assert_allclose(net.get_flat_params(), net2.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
    # sniffing loader
    net3 = ModelSerializer.restore(path)
    assert isinstance(net3, MultiLayerNetwork)


def test_listeners():
    X, Y = _toy_classification(n=64)
    net = MultiLayerNetwork(_mlp_conf()).init()
    coll = CollectScoresIterationListener()
    net.set_listeners(ScoreIterationListener(100, log_fn=lambda s: None), coll)
    net.fit(INDArrayDataSetIterator(X, Y, 32), epochs=2)
    assert len(coll.scores) == 4


def test_async_iterator_equivalence():
    X, Y = _toy_classification(n=64)
    base = INDArrayDataSetIterator(X, Y, 16)
    async_it = AsyncDataSetIterator(INDArrayDataSetIterator(X, Y, 16))
    batches_a = [ds.features.shape for ds in base]
    batches_b = [ds.features.shape for ds in async_it]
    assert batches_a == batches_b
    async_it.reset()
    assert sum(1 for _ in async_it) == 4


def test_rnn_fit_and_time_step():
    """Char-RNN style next-step prediction; streaming rnnTimeStep equals full
    forward (reference: MultiLayerTestRNN.java)."""
    rng = np.random.default_rng(0)
    b, t, f = 4, 8, 5
    x = rng.normal(size=(b, t, f)).astype(np.float32)
    y = np.eye(f, dtype=np.float32)[rng.integers(0, f, (b, t))]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .list()
            .layer(GravesLSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=f, activation="softmax", loss="MCXENT"))
            .input_type(InputType.recurrent(f))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(DataSet(x, y))
    net.fit(ListDataSetIterator([DataSet(x, y)]), epochs=20)
    assert net.score(DataSet(x, y)) < s0
    # streaming: feed steps one at a time, compare with full output
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    outs = [np.asarray(net.rnn_time_step(x[:, i])) for i in range(t)]
    streamed = np.stack(outs, axis=1)
    np.testing.assert_allclose(full, streamed, rtol=1e-4, atol=1e-5)


def test_tbptt_runs():
    rng = np.random.default_rng(0)
    b, t, f = 2, 12, 4
    x = rng.normal(size=(b, t, f)).astype(np.float32)
    y = np.eye(f, dtype=np.float32)[rng.integers(0, f, (b, t))]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .list()
            .layer(GravesLSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=f, activation="softmax", loss="MCXENT"))
            .input_type(InputType.recurrent(f))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .tbptt_fwd_length(4).tbptt_back_length(4)
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(DataSet(x, y))
    for _ in range(15):
        net.fit_batch(DataSet(x, y))
    assert net.score(DataSet(x, y)) < s0


def test_dropout_train_vs_inference():
    X, Y = _toy_classification(n=32)
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Sgd(0.1)).dropout(0.5)
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    # inference is deterministic (no dropout)
    o1 = np.asarray(net.output(X))
    o2 = np.asarray(net.output(X))
    np.testing.assert_allclose(o1, o2)
    net.fit(INDArrayDataSetIterator(X, Y, 16), epochs=2)  # runs with dropout


def test_cnn_pipeline_shapes():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 12, 12, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 2)]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4, activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=10, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(12, 12, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    out = net.output(x)
    assert out.shape == (2, 3)
    net.fit_batch(DataSet(x, y))
