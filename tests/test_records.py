"""DataVec-bridge tests: record readers → DataSet iterators → training.

Mirrors the reference's datasets/datavec test coverage
(deeplearning4j-core/src/test/java/org/deeplearning4j/datasets/datavec/
RecordReaderDataSetiteratorTest.java, RecordReaderMultiDataSetIteratorTest.java):
CSV classification/regression, image-folder training end-to-end, sequence
readers with alignment + masks.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, ConvolutionLayer, SubsamplingLayer,
                                MultiLayerNetwork, Adam, AsyncDataSetIterator)
from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader,
    CollectionRecordReader, RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
    AlignmentMode)


def _write_csv(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(",".join(str(v) for v in r) + "\n")


# ------------------------------------------------------------------- CSV

def test_csv_classification_iterator(tmp_path):
    rng = np.random.default_rng(0)
    rows = []
    for _ in range(64):
        cls = int(rng.integers(0, 3))
        feats = rng.normal(loc=cls, size=4)
        rows.append(list(np.round(feats, 4)) + [cls])
    p = tmp_path / "train.csv"
    _write_csv(p, rows)

    reader = CSVRecordReader().initialize(str(p))
    it = RecordReaderDataSetIterator(reader, 16, label_index=4,
                                     num_possible_labels=3)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].features.shape == (16, 4)
    assert batches[0].labels.shape == (16, 3)
    # labels one-hot match the csv
    assert np.argmax(batches[0].labels[0]) == rows[0][-1]
    it.reset()
    assert it.has_next()


def test_csv_header_skip_and_negative_label_index(tmp_path):
    p = tmp_path / "d.csv"
    _write_csv(p, [["a", "b", "label"], [1.0, 2.0, 1], [3.0, 4.0, 0]])
    reader = CSVRecordReader(skip_lines=1).initialize(str(p))
    it = RecordReaderDataSetIterator(reader, 2, label_index=-1,
                                     num_possible_labels=2)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    assert np.argmax(ds.labels[0]) == 1 and np.argmax(ds.labels[1]) == 0


def test_csv_regression_iterator(tmp_path):
    p = tmp_path / "r.csv"
    _write_csv(p, [[1, 2, 10, 20], [3, 4, 30, 40]])
    reader = CSVRecordReader().initialize(str(p))
    it = RecordReaderDataSetIterator(reader, 2, label_index_from=2,
                                     label_index_to=3, regression=True)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_allclose(ds.labels, [[10, 20], [30, 40]])


def test_csv_end_to_end_training(tmp_path):
    """Train an MLP from a CSV file on disk (the reference's canonical
    RecordReaderDataSetIterator workflow)."""
    rng = np.random.default_rng(1)
    rows = []
    for _ in range(256):
        cls = int(rng.integers(0, 2))
        feats = rng.normal(loc=2.0 * cls, scale=0.5, size=3)
        rows.append(list(np.round(feats, 4)) + [cls])
    p = tmp_path / "train.csv"
    _write_csv(p, rows)
    reader = CSVRecordReader().initialize(str(p))
    it = RecordReaderDataSetIterator(reader, 32, label_index=3,
                                     num_possible_labels=2)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=15)
    it.reset()
    acc = net.evaluate(it).accuracy()
    assert acc > 0.9


# ----------------------------------------------------------------- images

def _make_image_tree(root, n_per_class=12, size=12):
    from PIL import Image
    rng = np.random.default_rng(3)
    for label, base in (("dark", 40), ("bright", 200)):
        d = os.path.join(root, label)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            arr = np.clip(rng.normal(base, 20, (size, size)), 0, 255)
            Image.fromarray(arr.astype(np.uint8), "L").save(
                os.path.join(d, f"{i}.png"))


def test_image_record_reader_and_training(tmp_path):
    """Train a small CNN from a directory of PNGs end-to-end (reference:
    ImageRecordReader + ParentPathLabelGenerator workflow)."""
    _make_image_tree(str(tmp_path))
    reader = ImageRecordReader(height=12, width=12, channels=1)
    reader.initialize(str(tmp_path))
    assert reader.labels == ["bright", "dark"]
    it = RecordReaderDataSetIterator(reader, 8, num_possible_labels=2)
    ds = it.next()
    assert ds.features.shape == (8, 12, 12, 1)
    assert ds.labels.shape == (8, 2)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(12, 12, 1)).build())
    net = MultiLayerNetwork(conf).init()
    it.reset()
    net.fit(AsyncDataSetIterator(it), epochs=10)
    it.reset()
    assert net.evaluate(it).accuracy() > 0.9


def test_image_record_reader_label_indices_deterministic(tmp_path, monkeypatch):
    """Regression: the class-subdirectory -> label-index mapping (and the
    file order within each class) must not depend on filesystem enumeration
    order — os.listdir order is explicitly arbitrary and differs across
    filesystems. Scrambling listdir must change nothing."""
    for d in ("zebra", "ant", "mouse"):
        os.makedirs(tmp_path / d)
        for f in ("3.png", "1.png", "2.png"):
            (tmp_path / d / f).touch()     # initialize() only scans names
    (tmp_path / "notes.txt").touch()       # non-directory entries ignored

    reader = ImageRecordReader(height=4, width=4, channels=1)
    reader.initialize(str(tmp_path))
    baseline = (list(reader.labels), list(reader._items))

    real_listdir = os.listdir

    def scrambled(path):
        return list(reversed(sorted(real_listdir(path))))

    monkeypatch.setattr(os, "listdir", scrambled)
    reader2 = ImageRecordReader(height=4, width=4, channels=1)
    reader2.initialize(str(tmp_path))
    monkeypatch.undo()

    assert reader2.labels == ["ant", "mouse", "zebra"]
    assert (list(reader2.labels), list(reader2._items)) == baseline


# -------------------------------------------------------------- sequences

def test_sequence_two_reader_classification(tmp_path):
    fdir = tmp_path / "feat"
    ldir = tmp_path / "lab"
    fdir.mkdir(), ldir.mkdir()
    lengths = [3, 5, 4]
    for si, T in enumerate(lengths):
        _write_csv(fdir / f"{si}.csv", [[si + t, 10 * si + t] for t in range(T)])
        _write_csv(ldir / f"{si}.csv", [[si % 2] for _ in range(T)])
    fr = CSVSequenceRecordReader().initialize(str(fdir))
    lr = CSVSequenceRecordReader().initialize(str(ldir))
    it = SequenceRecordReaderDataSetIterator(
        fr, 3, num_possible_labels=2, labels_reader=lr,
        alignment_mode=AlignmentMode.ALIGN_START)
    ds = it.next()
    assert ds.features.shape == (3, 5, 2)
    assert ds.labels.shape == (3, 5, 2)
    # masks mark real steps (ALIGN_START: pad at the end)
    np.testing.assert_allclose(ds.features_mask[0], [1, 1, 1, 0, 0])
    np.testing.assert_allclose(ds.features_mask[1], [1, 1, 1, 1, 1])
    # first sequence's first step = [0, 0], second's = [1, 10]
    np.testing.assert_allclose(ds.features[1, 0], [1, 10])
    assert np.argmax(ds.labels[1, 0]) == 1


def test_sequence_align_end_and_single_reader(tmp_path):
    d = tmp_path / "seq"
    d.mkdir()
    _write_csv(d / "0.csv", [[0.1, 0.2, 1], [0.3, 0.4, 1]])
    _write_csv(d / "1.csv", [[0.5, 0.6, 0], [0.7, 0.8, 0], [0.9, 1.0, 0]])
    fr = CSVSequenceRecordReader().initialize(str(d))
    it = SequenceRecordReaderDataSetIterator(
        fr, 2, num_possible_labels=2, label_index=2,
        alignment_mode=AlignmentMode.ALIGN_END)
    ds = it.next()
    assert ds.features.shape == (2, 3, 2)
    # ALIGN_END: shorter sequence padded at the start
    np.testing.assert_allclose(ds.features_mask[0], [0, 1, 1])
    np.testing.assert_allclose(ds.features[0, 1], [0.1, 0.2])
    assert np.argmax(ds.labels[0, 1]) == 1


def test_multi_dataset_iterator():
    rec = [[1.0, 2.0, 3.0, 0], [4.0, 5.0, 6.0, 1], [7.0, 8.0, 9.0, 2]]
    it = (RecordReaderMultiDataSetIterator.Builder(2)
          .add_reader("r", CollectionRecordReader(rec))
          .add_input("r", 0, 1)
          .add_output_one_hot("r", 3, 3)
          .build())
    mds = it.next()
    assert mds.features[0].shape == (2, 2)
    assert mds.labels[0].shape == (2, 3)
    np.testing.assert_allclose(mds.features[0], [[1, 2], [4, 5]])
    assert np.argmax(mds.labels[0][1]) == 1
    assert it.has_next()
    it.next()
    assert not it.has_next()
    it.reset()
    assert it.has_next()
