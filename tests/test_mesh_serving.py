"""Mesh-sharded serving (serving/mesh.py): one dispatch, all chips.

Pins the tentpole's contracts on the 8-virtual-device CPU mesh:

- /predict and /generate under mesh dispatch are bit-comparable (f32
  tolerance; token-exact for greedy decode) to single-chip serving, for
  MultiLayerNetwork AND ComputationGraph — including int8-quantized
  weights placed under tensor-parallel sharding;
- a model whose global footprint exceeds a per-chip budget demonstrably
  serves once TP-sharded (the OOM proxy: per-chip bytes < budget < total
  bytes — real OOM is not reproducible on a shared-host CPU mesh);
- zero steady-state recompiles: compile counters and XLA executable cache
  sizes stay flat across repeated mesh waves (GL011's invariant survives
  the sharded cache + out_shardings pinning);
- the fleet plane counts GROUPS: a mesh replica is ONE ReplicaHandle (one
  breaker, one cohort member), the never-empty guard and autoscaler
  min/max/step math count handles, and chips surface as display/capacity
  gauges only;
- per-shard accounting: DecodeEngine.cache_bytes(per_shard=True) and the
  scheduler's decode_cache_mb gauge report what ONE chip holds.
"""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.decode.engine import DecodeEngine
from deeplearning4j_tpu.parallel.sharding import (
    MODEL_AXIS, ShardingRules, even_sharding, make_mesh,
    match_partition_rules, spec_shards)
from deeplearning4j_tpu.serving.mesh import (MeshContext, MeshDispatcher,
                                             MeshServingConfig)
from deeplearning4j_tpu.zoo.models import char_rnn_lstm, transformer_lm

V = 24


def _mln(seed=0, nin=6, nout=3):
    from tools.smoke_telemetry import _tiny_net
    return _tiny_net(nin=nin, nout=nout, seed=seed)


def _graph_lm(seed=7, heads=2):
    return transformer_lm(vocab_size=V, d_model=32, n_layers=2,
                          n_heads=heads, seed=seed).init()


def _rnn(seed=3):
    return char_rnn_lstm(vocab_size=V, hidden=16, layers=1,
                         seed=seed).init()


def _onehot_batch(rng, rows, L):
    return np.eye(V, dtype=np.float32)[rng.integers(0, V, (rows, L))]


# ------------------------------------------------------------ config/rules

def test_mesh_config_from_spec_forms():
    assert MeshServingConfig.from_spec(None) is None
    c = MeshServingConfig.from_spec(True)
    assert c.n_data is None and c.n_model == 1 and c.rules is None
    c = MeshServingConfig.from_spec(2)
    assert c.n_model == 2 and c.resolve_rules().rules  # tensor_parallel
    c = MeshServingConfig.from_spec({"n_data": 2, "n_model": 4,
                                     "rules": "tensor_parallel"})
    assert (c.n_data, c.n_model) == (2, 4)
    assert c.to_dict() == {"n_data": 2, "n_model": 4,
                           "rules": "tensor_parallel"}
    with pytest.raises(TypeError):
        MeshServingConfig.from_spec(3.5)
    with pytest.raises(ValueError):
        MeshServingConfig(rules="bogus").resolve_rules()


def test_match_partition_rules_specs_and_even_fallback():
    m = _mln()
    specs = match_partition_rules(ShardingRules.tensor_parallel_dense(),
                                  m.params)
    flat = {"/".join(str(p) for p in path): s for path, s in
            jax.tree_util.tree_flatten_with_path(specs)[0]}
    w = [s for k, s in flat.items() if k.endswith("['W']")]
    b = [s for k, s in flat.items() if k.endswith("['b']")]
    assert w and all(s == P(None, MODEL_AXIS) for s in w)
    assert b and all(s == P(MODEL_AXIS) for s in b)
    # even_sharding degrades a non-divisible partitioned dim to replicated
    mesh = make_mesh(n_data=2, n_model=4)
    ok = even_sharding(mesh, P(None, MODEL_AXIS), (3, 8))
    assert ok.spec == P(None, MODEL_AXIS)
    odd = even_sharding(mesh, P(None, MODEL_AXIS), (3, 7))
    assert odd.spec == P()
    assert spec_shards(mesh, ok.spec) == 4
    assert spec_shards(mesh, odd.spec) == 1


# ---------------------------------------------------------- /predict parity

@pytest.mark.parametrize("spec", [
    {"n_data": 8, "n_model": 1, "rules": None},
    {"n_data": 4, "n_model": 2, "rules": "tensor_parallel"},
], ids=["data_parallel", "tensor_parallel"])
def test_mesh_predict_parity_multilayernetwork(spec):
    m = _mln(seed=11)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 6)).astype(np.float32)   # 5 rows: forces padding
    want = np.asarray(m.output(x))
    w = MeshContext(spec).wrap(m)
    assert isinstance(w, MeshDispatcher)
    got = np.asarray(w.output(x))
    assert got.shape == want.shape                   # pad rows sliced off
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    assert w.mesh_context.dispatches == 1
    # idempotent wrap: the registry adapter may see a wrapped model again
    assert MeshContext(spec).wrap(w) is w


def test_mesh_predict_parity_computation_graph():
    g = _graph_lm(seed=12)
    rng = np.random.default_rng(1)
    x = _onehot_batch(rng, 3, 5)
    want = np.asarray(g.output(x))
    ctx = MeshContext({"n_data": 4, "n_model": 2, "rules": "tensor_parallel"})
    got = np.asarray(ctx.wrap(g).output(x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    # TP actually placed: some weight leaf spans the model axis
    specs = {str(l.sharding.spec) for l in
             jax.tree_util.tree_leaves(g.params) if hasattr(l, "sharding")}
    assert any(MODEL_AXIS in s for s in specs), specs


def test_mesh_int8_weights_parity_under_tp():
    """int8 serving weights compose with TP placement: the placed leaves
    ARE the codes (same W shapes), parity holds through the wrapper, and
    a dequantize re-places cleanly (identity-based re-placement)."""
    ref = _mln(seed=21)
    ref.quantize_weights("int8")
    x = np.random.default_rng(2).normal(size=(4, 6)).astype(np.float32)
    want = np.asarray(ref.output(x))

    m = _mln(seed=21)
    ctx = MeshContext({"n_data": 4, "n_model": 2, "rules": "tensor_parallel"})
    w = ctx.wrap(m)
    w.output(x)                       # place the f32 weights first
    w.quantize_weights("int8")        # delegates; swaps the params object
    got = np.asarray(w.output(x))     # must re-place the NEW (code) leaves
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    codes = [l for l in jax.tree_util.tree_leaves(m.params)
             if l.dtype == np.int8]
    assert codes, "int8 codes not placed in the params tree"
    assert any(MODEL_AXIS in str(l.sharding.spec) for l in codes)
    per, total = w.param_shard_bytes()
    assert per < total                # the diet composes with TP capacity
    w.dequantize_weights()
    np.testing.assert_allclose(np.asarray(w.output(x)),
                               np.asarray(_mln(seed=21).output(x)),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------- /generate parity

@pytest.mark.parametrize("make,label", [(_graph_lm, "graph_lm"),
                                        (_rnn, "mln_rnn")])
def test_mesh_generate_parity_and_sharded_cache(make, label):
    prompt = [3, 1, 4, 9, 2]

    def greedy(eng, n=6):
        cache = eng.init_cache()
        cache, nid, _ = eng.prefill(cache, 0, np.asarray(prompt, np.int32))
        out = [int(np.asarray(nid))]
        ids = np.zeros((eng.slots,), np.int32)
        for _ in range(n):
            ids[0] = out[-1]
            cache, nxt, _ = eng.step(cache, ids)
            out.append(int(np.asarray(nxt)[0]))
        return out

    want = greedy(DecodeEngine(make(), slots=2, max_len=32))
    ctx = MeshContext({"n_data": 4, "n_model": 2, "rules": "tensor_parallel"})
    eng = DecodeEngine(ctx.wrap(make()), slots=2, max_len=32)
    assert eng.mesh is ctx
    got = greedy(eng)
    assert got == want, label
    # the cache is genuinely partitioned -> per-shard bytes < global bytes
    per, total = eng.cache_bytes(per_shard=True), eng.cache_bytes()
    assert per < total, label
    # zero steady state: one executable per label even under shardings
    assert all(v == 1 for v in eng.executable_counts().values())


def test_decode_scheduler_cache_gauge_reports_per_shard_mb():
    from deeplearning4j_tpu.decode.scheduler import DecodeScheduler
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    ctx = MeshContext({"n_data": 4, "n_model": 2, "rules": "tensor_parallel"})
    reg = ModelRegistry(adapter=ctx.wrap)
    reg.register("v1", _graph_lm(seed=5))
    reg.deploy("v1")
    sched = DecodeScheduler(reg, MetricsRegistry(), slots=2, max_len=32)
    sched.start()
    try:
        sched.generate([1, 2, 3], max_new_tokens=2)
        eng = sched._engine
        want_mb = eng.cache_bytes(per_shard=True) / 1e6
        assert sched.cache_mb() == pytest.approx(want_mb)
        assert sched.cache_mb() < eng.cache_bytes() / 1e6
        assert sched.snapshot()["cache_mb"] == pytest.approx(want_mb)
        g = sched.metrics_registry.get("decode_cache_mb")
        assert g is not None and g.get() == pytest.approx(want_mb)
    finally:
        sched.stop()


# ------------------------------------------------------------- OOM proxy

def test_model_that_overflows_one_chip_serves_tp_sharded():
    """The capacity claim as a measurement: a dense model whose weight
    bytes exceed a per-chip budget fits per-chip once TP-sharded — and a
    forward actually runs under that placement. (Real OOM cannot be forced
    on a shared-host CPU mesh; the byte ledger is the honest proxy.)"""
    from deeplearning4j_tpu import (DenseLayer, InputType,
                                    MultiLayerNetwork,
                                    NeuralNetConfiguration, OutputLayer, Sgd)
    hidden = 512
    conf = (NeuralNetConfiguration.builder()
            .seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=8, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(64))
            .build())
    m = MultiLayerNetwork(conf).init()
    ctx = MeshContext({"n_data": 1, "n_model": 8, "rules": "tensor_parallel"})
    w = ctx.wrap(m)
    per, total = w.param_shard_bytes()
    budget = total // 3               # a chip one-third the model's size
    assert total > budget, "model must overflow the unsharded budget"
    assert per < budget, (per, budget, total)
    out = np.asarray(w.output(np.zeros((2, 64), np.float32)))
    assert out.shape == (2, 8)


# --------------------------------------------------- zero-recompile serving

def test_mesh_server_steady_state_compiles_flat():
    from deeplearning4j_tpu.serving.server import ServingServer
    srv = ServingServer(_mln(seed=31), max_batch_size=4,
                        mesh={"n_data": 4, "n_model": 2,
                              "rules": "tensor_parallel"}).start()
    try:
        x = np.random.default_rng(3).normal(size=(2, 6)).astype(np.float32)
        srv.submit(x).result(timeout=120)            # warm the (2, 6) bucket
        reg = srv.metrics.registry
        c0 = reg.get("compiles_total").get()
        jit = reg.get("jit_compiles_total")
        j0 = jit.get() if jit is not None else 0.0
        for _ in range(3):                           # steady-state waves
            out = srv.submit(x).result(timeout=120)
            assert len(out["prediction"]) == 2
        assert reg.get("compiles_total").get() == c0
        if jit is not None:
            assert jit.get() == j0
        assert srv.mesh.chips == 8
        assert reg.get("mesh_dispatch_chips").get() == 8.0
    finally:
        srv.stop()


# ------------------------------------------------------------- fleet plane

def test_fleet_counts_groups_not_chips_in_mixed_pool():
    from deeplearning4j_tpu.elastic import AutoscaleController, AutoscalePolicy
    from deeplearning4j_tpu.serving.frontend import FleetFrontend
    from deeplearning4j_tpu.serving.server import ServingServer

    mesh_srv = ServingServer(_mln(seed=41), mesh=True).start()
    solo_srv = ServingServer(_mln(seed=41)).start()
    fe = FleetFrontend([mesh_srv.url, solo_srv.url],
                       names=["mesh", "solo"], health_interval_s=0.0).start()
    try:
        fe.poll_health(force=True)
        by_name = {r.name: r for r in fe.replicas}
        # ONE handle for the 8-chip group; chips is display info on it
        assert len(fe.replicas) == 2
        assert by_name["mesh"].chips == 8 and by_name["solo"].chips == 1
        assert by_name["mesh"].to_dict()["chips"] == 8
        _, pool = fe._probe_pool()
        assert pool["replicas"] == 2 and pool["chips"] == 9

        class _NoLauncher:
            def launch(self, name):
                raise AssertionError("no scaling expected")
            terminate = launch

            def names(self):
                return []

        ctl = AutoscaleController(
            fe, _NoLauncher(),
            AutoscalePolicy(min_replicas=1, max_replicas=4, step=1),
            interval_s=0.0)
        sig = ctl.collect_signals()
        # policy math counts GROUPS (2), chips is the capacity gauge (9)
        assert sig["replicas"] == 2 and sig["chips"] == 9
        assert fe.registry.get("autoscale_replicas").get() == 2.0
        assert fe.registry.get("autoscale_chips").get() == 9.0

        # the never-empty guard counts handles: with solo removed, the mesh
        # group alone is "the last replica" no matter its 8 chips
        fe.remove_replica("solo")
        with pytest.raises(ValueError):
            fe.remove_replica("mesh")
    finally:
        fe.stop()
        mesh_srv.stop()
        solo_srv.stop()


# ------------------------------------------------------------- smoke tool

def test_smoke_mesh_tool():
    """Tier-1 wiring for tools/smoke_mesh.py: multi-device mesh deploy,
    concurrent /predict + /generate waves with single-chip parity, zero
    steady-state recompiles, canary rollback on the mesh replica as one
    unit, zero client 5xx (mirrors the smoke_decode/smoke_fleet wiring)."""
    import tools.smoke_mesh as smoke
    out = smoke.run(n_predict=6, n_generate=3, max_new_tokens=4)
    assert out["steady_state_compiles"] == 0
    assert out["donation_warnings"] == 0
    assert out["client_errors"] == 0
    assert out["gen_parity"]
    assert out["devices"] == 8
    assert out["pool"] == {"replicas": 2, "routable": 2, "chips": 9}
