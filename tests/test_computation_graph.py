"""ComputationGraph tests (reference: nn/graph tests +
GradientCheckTestsComputationGraph.java — every vertex type).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, RnnOutputLayer, GravesLSTM,
                                ComputationGraph, MultiDataSet, DataSet,
                                ElementWiseVertex, MergeVertex, SubsetVertex,
                                StackVertex, UnstackVertex, ScaleVertex,
                                L2NormalizeVertex, L2Vertex, LastTimeStepVertex,
                                DuplicateToTimeSeriesVertex, Adam, NoOp,
                                ComputationGraphConfiguration, ModelSerializer)


def _simple_graph_conf(nin=4, nout=3):
    return (NeuralNetConfiguration.builder()
            .seed(42).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_out=16, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=nout, activation="softmax",
                                          loss="MCXENT"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(nin))
            .build())


def test_graph_fit():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    w = rng.normal(size=(4, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, axis=1)]
    g = ComputationGraph(_simple_graph_conf()).init()
    s0 = g.score(DataSet(X, Y))
    g.fit([MultiDataSet([X], [Y])], epochs=30)
    assert g.score(DataSet(X, Y)) < s0 * 0.5
    out = g.output(X)
    assert out.shape == (128, 3)


def test_graph_json_roundtrip():
    conf = _simple_graph_conf()
    j = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(j)
    g1 = ComputationGraph(conf).init()
    g2 = ComputationGraph(conf2).init(params=g1.params)
    x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g1.output(x)), np.asarray(g2.output(x)),
                               rtol=1e-6)


def test_graph_serializer_roundtrip(tmp_path):
    g = ComputationGraph(_simple_graph_conf()).init()
    path = str(tmp_path / "graph.zip")
    ModelSerializer.write_model(g, path)
    g2 = ModelSerializer.restore(path)
    assert isinstance(g2, ComputationGraph)
    x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g.output(x)), np.asarray(g2.output(x)),
                               rtol=1e-6)


def test_multi_input_merge_and_elementwise():
    rng = np.random.default_rng(1)
    x1 = rng.normal(size=(8, 3)).astype(np.float32)
    x2 = rng.normal(size=(8, 3)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=5, activation="tanh"), "b")
            .add_vertex("sum", ElementWiseVertex("add"), "da", "db")
            .add_vertex("merge", MergeVertex(), "da", "db")
            .add_vertex("scaled", ScaleVertex(0.5), "sum")
            .add_vertex("norm", L2NormalizeVertex(), "merge")
            .add_vertex("cat", MergeVertex(), "scaled", "norm")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "cat")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(3), InputType.feed_forward(3))
            .build())
    g = ComputationGraph(conf).init()
    md = MultiDataSet([x1, x2], [Y])
    s0 = g.score(md_to_ds(md)) if False else None
    g.fit([md], epochs=20)
    out = g.output(x1, x2)
    assert out.shape == (8, 2)


def md_to_ds(md):
    return DataSet(md.features[0], md.labels[0])


def test_subset_stack_unstack():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(6, 8)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_vertex("first4", SubsetVertex(0, 3), "in")
            .add_vertex("last4", SubsetVertex(4, 7), "in")
            .add_vertex("stacked", StackVertex(), "first4", "last4")
            .add_layer("d", DenseLayer(n_out=6, activation="tanh"), "stacked")
            .add_vertex("u0", UnstackVertex(0, 2), "d")
            .add_vertex("u1", UnstackVertex(1, 2), "d")
            .add_vertex("joined", MergeVertex(), "u0", "u1")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "joined")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(8))
            .build())
    g = ComputationGraph(conf).init()
    g.fit([MultiDataSet([x], [Y])], epochs=5)
    assert g.output(x).shape == (6, 2)


def test_rnn_vertices_seq2seq_style():
    """LastTimeStep + DuplicateToTimeSeries (reference:
    nn/conf/graph/rnn/*, seq2seq pattern)."""
    rng = np.random.default_rng(3)
    b, t, f = 4, 6, 5
    x = rng.normal(size=(b, t, f)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (b, t))]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("enc", GravesLSTM(n_out=7, activation="tanh"), "in")
            .add_vertex("last", LastTimeStepVertex("in"), "enc")
            .add_vertex("dup", DuplicateToTimeSeriesVertex("in"), "last")
            .add_layer("dec", GravesLSTM(n_out=7, activation="tanh"), "dup")
            .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                             loss="MCXENT"), "dec")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(f))
            .build())
    g = ComputationGraph(conf).init()
    s0 = g.score(DataSet(x, y))
    g.fit([MultiDataSet([x], [y])], epochs=15)
    assert g.score(DataSet(x, y)) < s0
    assert g.output(x).shape == (b, t, 3)


def test_l2_vertex_siamese():
    rng = np.random.default_rng(4)
    x1 = rng.normal(size=(8, 4)).astype(np.float32)
    x2 = rng.normal(size=(8, 4)).astype(np.float32)
    y = rng.random((8, 1)).astype(np.float32)
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=5, activation="tanh"), "b")
            .add_vertex("dist", L2Vertex(), "da", "db")
            .add_layer("out", OutputLayer(n_out=1, activation="sigmoid",
                                          loss="XENT"), "dist")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4), InputType.feed_forward(4))
            .build())
    g = ComputationGraph(conf).init()
    g.fit([MultiDataSet([x1, x2], [y])], epochs=5)
    assert g.output(x1, x2).shape == (8, 1)


def test_graph_gradient_check():
    """Vertex gradient check (reference: GradientCheckTestsComputationGraph)."""
    import jax, jax.numpy as jnp
    rng = np.random.default_rng(5)
    x1 = rng.normal(size=(4, 3))
    x2 = rng.normal(size=(4, 3))
    Y = np.eye(2)[rng.integers(0, 2, 4)]
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(NoOp()).dtype("float64")
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=4, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=4, activation="tanh"), "b")
            .add_vertex("sum", ElementWiseVertex("add"), "da", "db")
            .add_vertex("merge", MergeVertex(), "sum", "da")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="MCXENT"), "merge")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(3), InputType.feed_forward(3))
            .build())
    g = ComputationGraph(conf).init()
    inputs = [jnp.asarray(x1), jnp.asarray(x2)]
    labels = [jnp.asarray(Y)]
    grads, _ = g.compute_gradient_and_score(inputs, labels)

    def score_with(params):
        s, _ = g._loss(params, g.states, inputs, labels, train=False, rng=None)
        return float(s)

    eps = 1e-6
    leaves, treedef = jax.tree_util.tree_flatten(g.params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    for li, (arr, garr) in enumerate(zip(leaves, g_leaves)):
        flat = np.asarray(arr).ravel().copy()
        gf = np.asarray(garr).ravel()
        for i in range(min(flat.size, 10)):
            orig = flat[i]
            flat[i] = orig + eps
            nl = list(leaves); nl[li] = jnp.asarray(flat.reshape(arr.shape))
            sp = score_with(jax.tree_util.tree_unflatten(treedef, nl))
            flat[i] = orig - eps
            nl = list(leaves); nl[li] = jnp.asarray(flat.reshape(arr.shape))
            sm = score_with(jax.tree_util.tree_unflatten(treedef, nl))
            flat[i] = orig
            numeric = (sp - sm) / (2 * eps)
            denom = abs(numeric) + abs(gf[i])
            rel = abs(numeric - gf[i]) / denom if denom else 0.0
            assert rel < 1e-3 or abs(numeric - gf[i]) < 1e-8


def test_graph_tbptt_and_epoch_listeners():
    """TBPTT on a ComputationGraph carries LSTM state across windows and the
    fit() loop fires epoch listener hooks (reference: ComputationGraph.java
    TBPTT fit path + MLN listener parity)."""
    from deeplearning4j_tpu.nn.conf.configuration import BackpropType
    from deeplearning4j_tpu.optimize.listeners import IterationListener

    rng = np.random.default_rng(3)
    T, B, nin, nout = 12, 8, 5, 3
    X = rng.normal(size=(B, T, nin)).astype(np.float32)
    Y = np.eye(nout, dtype=np.float32)[rng.integers(0, nout, (B, T))]

    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(5e-3))
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_out=8, activation="tanh"), "in")
            .add_layer("out", RnnOutputLayer(n_out=nout, activation="softmax",
                                             loss="MCXENT"), "lstm")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(nin))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .tbptt_fwd_length(4)
            .build())
    g = ComputationGraph(conf).init()

    class Hooks(IterationListener):
        def __init__(self):
            self.starts = self.ends = self.iters = 0

        def on_epoch_start(self, model):
            self.starts += 1

        def on_epoch_end(self, model):
            self.ends += 1

        def iteration_done(self, model, iteration):
            self.iters += 1

    h = Hooks()
    g.set_listeners(h)
    s0 = g.score(MultiDataSet([X], [Y]))
    g.fit([MultiDataSet([X], [Y])], epochs=25)
    assert h.starts == 25 and h.ends == 25 and h.iters == 25
    assert np.isfinite(g.score_value)
    assert g.score(MultiDataSet([X], [Y])) < s0
    # stateful streaming inference still works after TBPTT training
    out = g.rnn_time_step(X[:, 0])
    assert out.shape == (B, nout)


def test_transformer_lm_trains_and_attention_gradcheck():
    """NEW model family: decoder-only transformer (attention + LayerNorm +
    residual vertices) built from the DSL; loss must drop on a learnable
    next-token task."""
    import numpy as np
    from deeplearning4j_tpu.zoo.models import transformer_lm
    from deeplearning4j_tpu.datasets.dataset import DataSet

    net = transformer_lm(vocab_size=16, d_model=32, n_layers=2, n_heads=2,
                         ffn_mult=2, seed=3)
    net.init()
    rng = np.random.default_rng(0)
    # learnable sequences: next token = (token + 1) % 16
    starts = rng.integers(0, 16, size=(16, 1))
    ids = (starts + np.arange(13)) % 16
    x = np.eye(16, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(16, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(x, y)
    s0 = None
    for i in range(30):
        net.fit_batch(ds)
        if i == 0:
            s0 = net.score_value
    assert net.score_value < s0 * 0.7, (s0, net.score_value)
    out = np.asarray(net.output(x[:2]))
    assert out.shape == (2, 12, 16)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_layer_normalization_gradients():
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    DenseLayer, OutputLayer,
                                    LayerNormalization, MultiLayerNetwork,
                                    NoOp, WeightInit)
    from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6))
    y = np.eye(3)[rng.integers(0, 3, 4)]
    conf = (NeuralNetConfiguration.builder().seed(1).updater(NoOp())
            .dtype("float64").weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(LayerNormalization())
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, x, y, print_results=True)


def test_mixture_of_experts_layer_trains_and_gradcheck():
    """MoE layer (expert parallelism capability): trains, gradients check,
    and expert weights shard over the model axis."""
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    MixtureOfExpertsLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, NoOp, Adam,
                                    WeightInit)
    from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients

    rng = np.random.default_rng(3)
    x = rng.normal(size=(6, 8))
    y = np.eye(3)[rng.integers(0, 3, 6)]
    conf = (NeuralNetConfiguration.builder().seed(1).updater(NoOp())
            .dtype("float64").weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=8, n_experts=4, top_k=4,
                                         activation="identity"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    # top_k=4 == n_experts: gating fully differentiable -> exact grad check
    assert check_gradients(net, x, y, print_results=True)

    # top-2 routing trains (loss drops) on f32
    conf2 = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
             .weight_init(WeightInit.XAVIER).list()
             .layer(MixtureOfExpertsLayer(n_out=16, n_experts=4, top_k=2,
                                          activation="identity"))
             .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
             .set_input_type(InputType.feed_forward(8))
             .build())
    net2 = MultiLayerNetwork(conf2).init()
    X = rng.normal(size=(64, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    Y = np.eye(3, dtype=np.float32)[np.argmax(X @ w, 1)]
    s0 = net2.score(DataSet(X, Y))
    for _ in range(30):
        net2.fit(DataSet(X, Y))
    assert net2.score(DataSet(X, Y)) < s0 * 0.6


def test_expert_parallel_sharding():
    """Expert weights sharded over the model axis (EP): step matches the
    replicated run."""
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu import (NeuralNetConfiguration, InputType,
                                    MixtureOfExpertsLayer, OutputLayer,
                                    MultiLayerNetwork, DataSet, Sgd,
                                    WeightInit)
    from deeplearning4j_tpu.parallel.sharding import (make_mesh,
                                                      ShardedTrainer,
                                                      ShardingRules)

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Sgd(0.05))
                .weight_init(WeightInit.XAVIER).list()
                .layer(MixtureOfExpertsLayer(n_out=16, n_experts=4, top_k=4,
                                             activation="identity"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    a, b = build(), build()
    a.fit_batch(DataSet(X, Y))
    mesh = make_mesh(n_data=2, n_model=4)
    rules = ShardingRules()
    rules.add(r"^0/(W1|W2|b1|b2)$", P("model"))  # expert axis over 'model' = EP
    tr = ShardedTrainer(b, mesh=mesh, rules=rules)
    tr.fit_batch(DataSet(X, Y))
    np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                               rtol=1e-5, atol=1e-6)
