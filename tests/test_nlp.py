"""NLP stack tests — mirroring the reference's word2vec/paragraphvectors/glove
test pattern (deeplearning4j-nlp src/test: Word2VecTests, ParagraphVectorsTest,
GloveTest): train on a tiny corpus and assert semantic structure (related words
more similar than unrelated)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    DefaultTokenizer, NGramTokenizer, DefaultTokenizerFactory, CommonPreprocessor,
    CollectionSentenceIterator, BasicLineIterator, LabelledDocument,
    VocabConstructor, Huffman, Word2Vec, ParagraphVectors, Glove,
    WordVectorSerializer, BagOfWordsVectorizer, TfidfVectorizer,
    CnnSentenceDataSetIterator, LabelsSource)


# corpus with two clear clusters: royalty and fruit
CORPUS = [
    "the king rules the castle with the queen",
    "the queen and the king sit on the throne",
    "the royal king wears a crown and the queen a tiara",
    "the prince will be king and the princess queen",
    "apple and banana are sweet fruit",
    "a ripe banana and a red apple are tasty fruit",
    "fruit like apple and banana grow on trees",
    "the orchard grows apple banana and other fruit",
] * 12


def test_tokenizer_and_preprocessor():
    t = DefaultTokenizer("Hello, World! 123 test")
    t.set_token_pre_processor(CommonPreprocessor())
    toks = t.get_tokens()
    assert "hello" in toks and "world" in toks
    assert all("123" not in x for x in toks)
    ng = NGramTokenizer("a b c", min_n=1, max_n=2).get_tokens()
    assert "a b" in ng and "b c" in ng and "a" in ng


def test_vocab_and_huffman():
    vc = VocabConstructor(min_word_frequency=2).build_vocab(CORPUS)
    assert vc.contains_word("king") and vc.contains_word("banana")
    # most frequent word gets index 0
    assert vc.word_at_index(0) == "the"
    kw = vc.word_for("king")
    assert len(kw.codes) > 0 and len(kw.codes) == len(kw.points)
    # Huffman: frequent words get shorter codes
    assert len(vc.word_for("the").codes) <= len(kw.codes)


def test_word2vec_semantic_clusters_hs():
    """Hierarchical softmax separates the two topic clusters on the tiny
    corpus (negative sampling needs more data for cluster geometry; its
    correctness is covered by the parity test below)."""
    stop = ["the", "and", "a", "are", "on", "with", "will", "be", "other",
            "like", "grow", "grows", "sit"]
    w2v = (Word2Vec.builder()
           .layer_size(32).window_size(4).epochs(15).seed(42)
           .min_word_frequency(2).learning_rate(0.05).stop_words(stop)
           .use_hierarchic_softmax().negative_sample(0)
           .iterate(CollectionSentenceIterator(CORPUS)).build())
    w2v.fit()
    related = w2v.similarity("king", "queen")
    unrelated = w2v.similarity("king", "banana")
    assert related > unrelated, (related, unrelated)


def _numpy_sequential_sgns(pairs, V, D, lr, n_neg, seed):
    """Plain sequential skip-gram-negative-sampling (the reference semantics:
    SkipGram.java iterateSample applied pair by pair)."""
    rng = np.random.default_rng(seed)
    syn0 = (rng.random((V, D)).astype(np.float32) - 0.5) / D
    syn1 = np.zeros((V, D), np.float32)

    def sig(x):
        return 1.0 / (1.0 + np.exp(-x))

    for c, o in pairs:
        v = syn0[c].copy()
        u = syn1[o]
        g = (1 - sig(v @ u)) * lr
        dv = g * u
        syn1[o] += g * v
        for _ in range(n_neg):
            n = rng.integers(0, V)
            if n == o:
                continue
            un = syn1[n]
            gn = -sig(v @ un) * lr
            dv += gn * un
            syn1[n] += gn * v
        syn0[c] += dv
    return syn0


@pytest.mark.parametrize("mode", ["ns", "cbow"])
def test_sgns_kernel_parity_with_sequential_reference(mode):
    """The batched XLA kernel must land in the same similarity structure as a
    pair-by-pair sequential word2vec (the reference's Hogwild semantics) —
    the analog of the reference's cuDNN-vs-java-path parity tests."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nlp.embeddings import (skipgram_ns_step,
                                                   cbow_ns_step, CHUNK)
    rng = np.random.default_rng(0)
    V, D, B, BLK = 40, 32, 256, 5
    # co-occurrence blocks of 5 words each
    pairs = []
    for _ in range(30000):
        blk = rng.integers(0, V // BLK) * BLK
        a, b = rng.choice(BLK, 2, replace=False) + blk
        pairs.append((a, b))
    pairs = np.array(pairs, np.int32)
    ref = _numpy_sequential_sgns(pairs, V, D, 0.05, 5, seed=1)

    key = jax.random.PRNGKey(0)
    s0 = jnp.asarray((np.random.default_rng(1).random((V, D)).astype(np.float32) - 0.5) / D)
    s1 = jnp.zeros((V, D), jnp.float32)
    unigram = jnp.arange(V, dtype=jnp.int32)
    for s in range(0, len(pairs) - B + 1, B):
        key, sub = jax.random.split(key)
        c = jnp.asarray(pairs[s:s + B, 0])
        o = jnp.asarray(pairs[s:s + B, 1])
        valid = jnp.ones((B,), jnp.float32)
        if mode == "ns":
            s0, s1 = skipgram_ns_step(s0, s1, unigram, c, o, valid, 0.05, sub, 5)
        else:
            s0, s1 = cbow_ns_step(s0, s1, unigram, o[:, None],
                                  jnp.ones((B, 1), jnp.float32), c, valid,
                                  0.05, sub, 5)
    W = np.asarray(s0)

    def cos(M, a, b):
        va, vb = M[a], M[b]
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-9))

    # same qualitative structure: in-block similarity beats cross-block,
    # in both the sequential reference and the batched kernel
    for name, M in (("sequential-ref", ref), ("xla-kernel", W)):
        in_block = np.mean([cos(M, i, i + 1) for i in range(0, V, BLK)])
        cross = np.mean([cos(M, i, (i + BLK) % V) for i in range(0, V, BLK)])
        assert in_block > cross, (name, in_block, cross)


def test_word2vec_serialization_roundtrip(tmp_path):
    w2v = (Word2Vec.builder().layer_size(16).epochs(2).seed(1)
           .min_word_frequency(2)
           .iterate(CollectionSentenceIterator(CORPUS)).build())
    w2v.fit()
    # text format
    p = tmp_path / "vecs.txt"
    WordVectorSerializer.write_word_vectors(w2v, p)
    model = WordVectorSerializer.load_static_model(p)
    assert np.allclose(model.get_word_vector("king"),
                       w2v.get_word_vector("king"), atol=1e-4)
    # google binary format
    pb = tmp_path / "vecs.bin"
    WordVectorSerializer.write_binary(w2v, pb)
    model_b = WordVectorSerializer.load_static_model(pb, binary=True)
    assert np.allclose(model_b.get_word_vector("queen"),
                       w2v.get_word_vector("queen"), atol=1e-6)


def test_paragraph_vectors_dbow():
    docs = ([("king queen castle royal throne crown palace knight", "royalty")] * 20 +
            [("apple banana fruit orchard ripe sweet juicy harvest", "food")] * 20)
    pv = ParagraphVectors(layer_size=24, epochs=60, seed=3, negative=5,
                          min_word_frequency=1, sequence_algo="dbow")
    pv.fit(docs)
    lv_r = pv.get_label_vector("royalty")
    lv_f = pv.get_label_vector("food")
    assert lv_r is not None and lv_f is not None and not np.allclose(lv_r, lv_f)

    # inferred doc vectors land closer to their topic's label vector
    assert pv.similarity_to_label("queen royal castle", "royalty") > \
        pv.similarity_to_label("queen royal castle", "food")
    assert pv.similarity_to_label("ripe banana sweet apple", "food") > \
        pv.similarity_to_label("ripe banana sweet apple", "royalty")

    iv = pv.infer_vector("queen rules the castle")
    assert iv.shape == (24,) and np.all(np.isfinite(iv))


def test_paragraph_vectors_dm():
    docs = ([("king queen castle royal throne crown", "royalty")] * 8 +
            [("apple banana fruit orchard ripe sweet", "food")] * 8)
    pv = ParagraphVectors(layer_size=16, epochs=15, seed=4, negative=5,
                          min_word_frequency=1, sequence_algo="dm")
    pv.fit(docs)
    assert pv.get_label_vector("royalty").shape == (16,)


def test_glove():
    g = (Glove.builder().layer_size(24).window_size(4).epochs(25)
         .learning_rate(0.1).min_word_frequency(2).seed(5).build())
    g.fit(CORPUS)
    assert g.loss_history[-1] < g.loss_history[0]  # training converges
    assert g.similarity("king", "queen") > g.similarity("king", "banana")


def test_bow_tfidf():
    texts = ["apple banana apple", "king queen", "apple king"]
    bow = BagOfWordsVectorizer().fit(texts)
    v = bow.transform("apple banana apple")
    assert v[bow.vocab.index_of("apple")] == 2
    assert v[bow.vocab.index_of("banana")] == 1
    tf = TfidfVectorizer().fit(texts)
    vt = tf.transform("apple banana")
    # banana appears in 1/3 docs, apple in 2/3 -> banana weighted higher
    assert vt[tf.vocab.index_of("banana")] > vt[tf.vocab.index_of("apple")]


def test_cnn_sentence_iterator():
    w2v = (Word2Vec.builder().layer_size(8).epochs(1).seed(6)
           .min_word_frequency(1)
           .iterate(CollectionSentenceIterator(CORPUS)).build())
    w2v.fit()
    data = [("king queen castle", "a"), ("apple banana", "b")] * 4
    it = CnnSentenceDataSetIterator(w2v, data, ["a", "b"], batch_size=4,
                                    max_sentence_length=6)
    ds = it.next()
    assert ds.features.shape == (4, 6, 8, 1)
    assert ds.labels.shape == (4, 2)
    assert ds.features_mask.shape == (4, 6)
    assert ds.features_mask[0].sum() == 3  # three known words


def test_basic_line_iterator(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("line one\nline two\nline three\n")
    it = BasicLineIterator(p)
    lines = list(it)
    assert lines == ["line one", "line two", "line three"]
    it.reset()
    assert it.next_sentence() == "line one"


def test_labels_source():
    ls = LabelsSource()
    a, b = ls.next_label(), ls.next_label()
    assert a == "DOC_0" and b == "DOC_1"
    assert ls.size() == 2
