"""Online inference serving + preemption-safe training.

Run: python examples/04_serving_and_fault_tolerance.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet,
                                ListDataSetIterator, Sgd)
from deeplearning4j_tpu.streaming import InferenceServer
from deeplearning4j_tpu.train import CheckpointConfig, FaultTolerantTrainer


def factory():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="MCXENT"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf)


rng = np.random.default_rng(0)
X = rng.random((256, 8)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 256)]

# checkpoint every 5 iterations; rerunning this script RESUMES automatically
trainer = FaultTolerantTrainer(factory, CheckpointConfig("/tmp/ft_demo",
                                                         frequency=5))
print("resumed from checkpoint:" if trainer.resumed else "fresh run:",
      trainer.state)
trainer.fit(ListDataSetIterator(DataSet(X, Y), batch_size=32), epochs=3)

# serve the trained model over HTTP
server = InferenceServer(trainer.model, port=0).start()
req = urllib.request.Request(server.url + "/predict",
                             data=json.dumps({"data": X[:2].tolist()}).encode())
with urllib.request.urlopen(req, timeout=30) as r:
    print("served prediction:", json.loads(r.read())["prediction"][0])
server.stop()
