"""Pipeline parallelism end to end: 1F1B training, gather, checkpoint.

Trains a deep MLP split into 4 pipeline stages (each stage's parameters on
its own device, microbatches streamed through the interleaved
one-forward-one-backward schedule as compiled per-stage XLA executables),
then gathers the model onto one device for inference and writes/restores a
sharded checkpoint. Runs on the 8-device virtual CPU mesh; the same code
drives real multi-chip TPU slices.

Run: python examples/06_pipeline_parallelism.py
"""
import os
import sys

# the demo needs SEVERAL devices: force the 8-device virtual CPU mesh (on a
# real multi-chip TPU slice, drop these two lines and the stages land on
# real chips)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu import (DataSet, DenseLayer, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer, Sgd)
from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
from deeplearning4j_tpu.util.sharded_checkpoint import (restore_sharded,
                                                        save_sharded)


def main():
    b = NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05)).list()
    for _ in range(6):
        b = b.layer(DenseLayer(n_out=128, activation="relu"))
        b = b.layer(BatchNormalization())
    conf = (b.layer(OutputLayer(n_out=5, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(32))
            .build())
    net = MultiLayerNetwork(conf).init()

    n_stages = min(4, len(jax.devices()))
    pt = PipelineTrainer(net, n_stages=n_stages, n_microbatches=8,
                        devices=jax.devices()[:n_stages])
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 32)).astype(np.float32)
    w = rng.normal(size=(32, 5))
    Y = np.eye(5, dtype=np.float32)[np.argmax(X @ w, axis=1)]

    print(f"training over {n_stages} pipeline stages x 8 microbatches "
          f"(BatchNorm stats update per microbatch)")
    for step in range(30):
        score = pt.fit_batch(DataSet(X, Y))
        if step % 10 == 0:
            print(f"  step {step}: loss {score:.4f}")

    pt.gather()          # re-colocate for inference/serialization
    preds = np.asarray(net.output(X))
    acc = (preds.argmax(1) == Y.argmax(1)).mean()
    print(f"post-gather inference accuracy on train set: {acc:.2f}")

    ckpt = "/tmp/pipeline_example_ckpt"
    save_sharded(net, ckpt)
    net2 = restore_sharded(ckpt)     # shardings re-derived from the meta
    assert np.allclose(np.asarray(net2.output(X)), preds, atol=1e-6)
    print("checkpoint round-trip: restored model predicts identically")


if __name__ == "__main__":
    main()
