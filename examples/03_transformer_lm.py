"""Train the decoder-only transformer LM (new model family) with bf16 mixed
precision and the Pallas flash-attention kernel.

Run: python examples/03_transformer_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.zoo.models import transformer_lm

VOCAB, SEQ = 64, 128
net = transformer_lm(vocab_size=VOCAB, d_model=128, n_layers=2, n_heads=2,
                     use_pallas=True, compute_dtype="bfloat16")
net.init()

rng = np.random.default_rng(0)
starts = rng.integers(0, VOCAB, size=(32, 1))
ids = (starts + np.arange(SEQ + 1)) % VOCAB     # learnable: next = cur + 1
x = np.eye(VOCAB, dtype=np.float32)[ids[:, :-1]]
y = np.eye(VOCAB, dtype=np.float32)[ids[:, 1:]]

for step in range(10):
    net.fit_batch(DataSet(x, y))
    if step % 5 == 0:
        print(f"step {step}: loss {net.score_value:.4f}")

# the hot-path way: K steps per compiled executable — one host dispatch per
# K optimizer steps (lax.scan with donated carry, nn/multistep.py); per-step
# scores stay available on device as net.last_scores
from deeplearning4j_tpu.datasets.iterator.base import ListDataSetIterator
net.fit(ListDataSetIterator([DataSet(x, y)] * 20), steps_per_execution=10)
print("scanned scores tail:",
      [round(float(s), 4) for s in np.asarray(net.last_scores)[-3:]])
print("final loss:", round(net.score_value, 4))
