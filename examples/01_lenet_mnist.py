"""LeNet on MNIST — the minimum end-to-end slice (BASELINE config #1).

Run: python examples/01_lenet_mnist.py
(MNIST falls back to a deterministic synthetic digit set when the real
download is unavailable; place the IDX files under ~/.deeplearning4j_tpu/mnist to
use real data.)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deeplearning4j_tpu import ModelSerializer, ScoreIterationListener
from deeplearning4j_tpu.datasets.fetchers.mnist import MnistDataSetIterator
from deeplearning4j_tpu.zoo.models import lenet_mnist

net = lenet_mnist()
net.init()
net.set_listeners(ScoreIterationListener(10))
train = MnistDataSetIterator(64, train=True, num_examples=1024)
test = MnistDataSetIterator(64, train=False, num_examples=256)

net.fit(train, epochs=5)
e = net.evaluate(test, top_n=3)
print(e.stats())
print("top-3 accuracy:", round(e.top_n_accuracy(), 4))

ModelSerializer.write_model(net, "/tmp/lenet.zip")
print("saved to /tmp/lenet.zip; restore with ModelSerializer.restore(path)")
