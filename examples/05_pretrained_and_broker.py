"""Pretrained zoo weights + streaming over the TCP broker.

Loads the committed pretrained LeNet (real handwritten digits), decodes
predictions to label names, then serves it as a streaming route: producers
publish image batches to a broker topic over TCP, the route runs the jitted
forward, and consumers poll predictions off another topic — the reduced
Kafka-serve-route shape of the reference's dl4j-streaming.

Run: python examples/05_pretrained_and_broker.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.datasets.fetchers.mnist import MnistDataSetIterator
from deeplearning4j_tpu.streaming import (BrokerClient, BrokerSink,
                                          BrokerSource, MessageBroker,
                                          NDArrayMessage, ServeRoute)
from deeplearning4j_tpu.zoo import load_pretrained


def main():
    # 1) pretrained weights -> ready-for-inference model + label table
    net, labels = load_pretrained("lenet_mnist_real")
    ds = MnistDataSetIterator(batch_size=8, train=False, shuffle=False).next()
    top = labels.decode_predictions(net.output(ds.features), top=1)
    truth = np.argmax(np.asarray(ds.labels), axis=1)
    print("pretrained top-1 vs truth:")
    for (label_prob,), t in zip(top, truth):
        print(f"  predicted {label_prob[0]!r} ({label_prob[1]:.2f})"
              f"  truth 'digit {t}'")

    # 2) the same model behind a broker-backed serve route
    broker = MessageBroker(port=0).start()
    route = ServeRoute(
        net,
        BrokerSource(BrokerClient(port=broker.port), "images"),
        BrokerSink(BrokerClient(port=broker.port), "predictions"))
    route.start()
    producer = BrokerClient(port=broker.port)
    consumer = BrokerClient(port=broker.port)
    feats = np.asarray(ds.features)
    for i in range(4):
        producer.publish("images",
                         NDArrayMessage(feats[i:i + 1], {"i": i}).to_dict())
    got = 0
    deadline = time.time() + 60
    while got < 4 and time.time() < deadline:
        d = consumer.poll("predictions", timeout=1)
        if d is None:
            continue
        m = NDArrayMessage.from_json(d)
        name, p = labels.decode_predictions(m.array, top=1)[0][0]
        print(f"  broker record {m.meta['i']}: {name!r} ({p:.2f})")
        got += 1
    route.stop()
    broker.stop()
    assert got == 4
    print("done: 4 predictions served over TCP")


if __name__ == "__main__":
    main()
