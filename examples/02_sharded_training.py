"""Data + tensor parallel training over a device mesh.

Run on one host: python examples/02_sharded_training.py
(uses all visible devices; to simulate a mesh on CPU:
 XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu ...)

Multi-host: call parallel.multihost.initialize(coordinator, N, i) in every
process first; everything below is unchanged (SPMD).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DenseLayer,
                                OutputLayer, MultiLayerNetwork, DataSet, Adam)
from deeplearning4j_tpu.parallel.sharding import (make_mesh, ShardedTrainer,
                                                  ShardingRules)

n = len(jax.devices())
# model axis only when the device count splits evenly; otherwise pure DP
mesh = make_mesh(n_model=2 if n % 2 == 0 and n >= 2 else 1)

conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_out=512, activation="relu"))
        .layer(DenseLayer(n_out=512, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="MCXENT"))
        .set_input_type(InputType.feed_forward(784))
        .build())
net = MultiLayerNetwork(conf).init()

rules = ShardingRules()                       # tensor parallelism on layer 0
rules.add(r"^0/W$", P(None, "model"))
rules.add(r"^0/b$", P("model"))
trainer = ShardedTrainer(net, mesh=mesh, rules=rules)

rng = np.random.default_rng(0)
X = rng.random((512, 784)).astype(np.float32)
Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 512)]
for step in range(20):
    trainer.fit_batch(DataSet(X, Y))
print("final score:", net.score_value)
