"""Console entry for graftlint (`[project.scripts] graftlint = ...`).

deeplearning4j_tpu/analysis is stdlib-only, but a plain import of
``deeplearning4j_tpu.analysis.cli`` executes the parent package __init__ —
jax and the whole framework, ~2.5s and an ImportError in jax-free lint
environments. This shim locates the package WITHOUT executing its __init__
(find_spec reads metadata only for a top-level name), installs an empty
parent-module stub, and only then imports the analysis subpackage. The
in-repo `tools/lint.py` wrapper reuses it.

`python -m deeplearning4j_tpu.analysis` remains the full-framework route
(the -m machinery necessarily imports the parent package).
"""
import importlib.util
import sys
import types


def _stub_parent_package():
    if "deeplearning4j_tpu" in sys.modules:
        return
    spec = importlib.util.find_spec("deeplearning4j_tpu")
    if spec is None or not spec.submodule_search_locations:
        raise ImportError("deeplearning4j_tpu package not found on sys.path")
    pkg = types.ModuleType("deeplearning4j_tpu")
    pkg.__path__ = list(spec.submodule_search_locations)
    pkg.__spec__ = spec
    sys.modules["deeplearning4j_tpu"] = pkg


def main(argv=None):
    _stub_parent_package()
    from deeplearning4j_tpu.analysis.cli import main as cli_main
    return cli_main(argv)


if __name__ == "__main__":
    sys.exit(main())
